"""Learned network-topology model: the sparse probe stream becomes
dense, confidence-weighted latency/bandwidth estimates.

The probe orchestrator's budget covers a vanishing fraction of the
pair space at scale (5k nodes = 12.5M pairs at 64 probes/cycle ≈ 54
hours per full sweep), so the ``lat``/``bw`` matrices the C-matrix and
gang placement consume are almost entirely unobserved zeros.  This
module treats the matrices as a MODEL fit on the probe stream instead
of a scraped cache:

- **Latency** — a Vivaldi-style coordinate embedding: each node gets a
  coordinate ``x[d]`` plus a non-negative "height" (access-link cost);
  predicted latency is ``||x_i - x_j|| + h_i + h_j``.  Racks/zones
  cluster in coordinate space after a few hundred observations.
- **Bandwidth** — low-rank matrix completion in log space:
  ``log1p(bw[i, j]) ≈ mu + su_i + sv_j + u_i · v_j`` with per-node
  up/downlink biases (``su``/``sv``) and rank-``r`` factors capturing
  the block structure of rack/zone tiers (a rack-membership indicator
  is rank-1, so small ``r`` suffices).

Both are trained by ONE jitted mini-batch Adam step over a fixed-size
host ring buffer of recent observations — shapes are static
(``batch`` observations of index/target/weight vectors), so the step
compiles exactly once per process; per-cycle refits are pure dispatch
(the acceptance bar the bench leg and tests pin).

``blend()`` merges model predictions into the probe matrices with two
weights per pair: direct-probe freshness ``exp(-age/tau)`` and model
confidence (a product of per-node observation-count saturations), so
fresh probes win, stale/absent pairs fall back to the model, and pairs
the model knows nothing about keep the raw probe value.  With the
model disabled the blend never runs — scoring stays bit-identical to
the pure probe matrices.

A residual monitor compares each fresh measurement against the current
prediction BEFORE ingesting it: a confident model disagreeing sharply
with a fresh probe is a link-degradation signal (surfaced as k8s
Events by serve.py and counted in self-metrics), not a training
detail.

Threading: ``observe``/``fit`` run on the probe-orchestrator thread;
``blend`` runs under the encoder lock on snapshot paths; all mutable
state is guarded by ``_lock`` (lock order: encoder lock, then model
lock — the model never calls back into the encoder).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig


class TopoParams(NamedTuple):
    """Model parameters (a JAX pytree; ``N = cfg.max_nodes``)."""

    x: jax.Array    # f32[N, d]  latency coordinates
    h: jax.Array    # f32[N]     access-link height (relu'd in predict)
    u: jax.Array    # f32[N, r]  bandwidth row factors
    v: jax.Array    # f32[N, r]  bandwidth col factors
    su: jax.Array   # f32[N]     per-node uplink bias (log-bw space)
    sv: jax.Array   # f32[N]     per-node downlink bias
    mu: jax.Array   # f32[]      global log-bandwidth level


def _pair_predict(params: TopoParams, i, j):
    """Predicted (lat_ms, log1p_bw) for observation index vectors."""
    delta = params.x[i] - params.x[j]
    dist = jnp.sqrt(jnp.sum(delta * delta, axis=-1) + 1e-6)
    lat = dist + jax.nn.relu(params.h[i]) + jax.nn.relu(params.h[j])
    y = (params.mu + params.su[i] + params.sv[j]
         + jnp.sum(params.u[i] * params.v[j], axis=-1))
    return lat, y


def _loss(params: TopoParams, i, j, lat_obs, y_obs, w_lat, w_bw):
    lat_hat, y_hat = _pair_predict(params, i, j)
    l_lat = (jnp.sum(w_lat * jnp.square(lat_hat - lat_obs))
             / (jnp.sum(w_lat) + 1e-6))
    l_bw = (jnp.sum(w_bw * jnp.square(y_hat - y_obs))
            / (jnp.sum(w_bw) + 1e-6))
    # Light factor decay: keeps unobserved rows near zero so the
    # row/col biases (not stale factors) carry never-probed nodes.
    reg = 1e-4 * (jnp.mean(jnp.square(params.u))
                  + jnp.mean(jnp.square(params.v)))
    return l_lat + l_bw + reg


# Polyak averaging horizon for the prediction parameters: ~500 steps.
# Predictions read an EMA of the Adam iterates, not the iterates
# themselves — mini-batch Adam orbits its optimum with a noise floor
# proportional to the rate, and that noise blurs exactly the
# same-rack block edges gang placement keys on.  Averaging removes
# the orbit without touching the training rate, which matters for
# INCREMENTAL ingest: probes arrive over hours, so the rate must stay
# high enough for late-discovered pairs to learn (measured at N=1024
# with probes split over 280 cycles: raw iterates recover 50% of the
# oracle placement gain; the EMA read recovers ~90%).
_EMA_DECAY = 0.998


def _sgd_step(params: TopoParams, m: TopoParams, v: TopoParams, t,
              ema: TopoParams, i, j, lat_obs, y_obs, w_lat, w_bw, lr):
    """THE jitted update: one Adam mini-batch step + the prediction-EMA
    accumulate, static shapes.

    Plain SGD is unusable here: the factor interaction ``u_i . v_j``
    gives the loss a curvature that grows with the factors themselves,
    so any global rate large enough to learn the rack-block structure
    in bounded steps diverges (measured at N=1024: lr 0.3 leaves the
    in-sample log residual at ~1.1 after 5k steps, lr 1.0 NaNs), and
    Adagrad's 1/sqrt(sum g^2) rate decays before the factors grow
    (stalls at ~1.0; the rank-8 SVD floor is ~0.085).  Adam's
    per-parameter normalized, non-decaying rate reaches the floor in a
    few thousand steps.  Rows with no gradient history (never-observed
    nodes) have zero moments and stay exactly at init.

    ``ema`` is zero-initialized and bias-corrected at read time
    (divide by ``1 - _EMA_DECAY**t``), mirroring Adam's own moment
    correction."""
    b1, b2, eps = 0.9, 0.999, 1e-8
    grads = jax.grad(_loss)(params, i, j, lat_obs, y_obs, w_lat, w_bw)
    t = t + 1
    m = TopoParams(*(b1 * a + (1 - b1) * g for a, g in zip(m, grads)))
    v = TopoParams(*(b2 * a + (1 - b2) * g * g
                     for a, g in zip(v, grads)))
    c1 = 1.0 - b1 ** t
    c2 = 1.0 - b2 ** t
    params = TopoParams(
        *(p - lr * (a / c1) / (jnp.sqrt(b / c2) + eps)
          for p, a, b in zip(params, m, v)))
    ema = TopoParams(*(_EMA_DECAY * e + (1.0 - _EMA_DECAY) * p
                       for e, p in zip(ema, params)))
    return params, m, v, t, ema


def _predict_dense(params: TopoParams):
    """Dense ``(lat_hat[N, N], bw_hat[N, N])`` from the parameters.

    Distances via the Gram identity (no N x N x d intermediate — at 5k
    nodes that would be a 400 MB temporary)."""
    sq = jnp.sum(params.x * params.x, axis=-1)
    d2 = sq[:, None] + sq[None, :] - 2.0 * (params.x @ params.x.T)
    dist = jnp.sqrt(jnp.maximum(d2, 0.0) + 1e-6)
    hh = jax.nn.relu(params.h)
    lat = dist + hh[:, None] + hh[None, :]
    y = (params.mu + params.su[:, None] + params.sv[None, :]
         + params.u @ params.v.T)
    # Clip the log-bandwidth before exp: an early-training outlier row
    # must saturate, not overflow f32 into inf (which would poison the
    # blended matrix's normalizers).
    bw = jnp.expm1(jnp.clip(y, 0.0, 60.0))
    return lat, bw


def _init_params(cfg: SchedulerConfig, seed: int) -> TopoParams:
    n, d, r = cfg.max_nodes, cfg.netmodel_dim, cfg.netmodel_rank
    rng = np.random.default_rng(seed)
    scale = 1.0 / np.sqrt(r)
    return TopoParams(
        x=jnp.asarray(0.1 * rng.standard_normal((n, d)).astype(np.float32)),
        h=jnp.zeros((n,), jnp.float32),
        u=jnp.asarray((scale * rng.standard_normal((n, r))).astype(np.float32)),
        v=jnp.asarray((scale * rng.standard_normal((n, r))).astype(np.float32)),
        su=jnp.zeros((n,), jnp.float32),
        sv=jnp.zeros((n,), jnp.float32),
        mu=jnp.zeros((), jnp.float32),
    )


class TopologyModel:
    """Topology estimator + ring buffer + confidence/residual state.

    One instance is sized to ``cfg.max_nodes`` and indexed by ENCODER
    node slot (the orchestrator resolves names before calling
    :meth:`observe`), so slot reuse after node removal flows through
    :meth:`reset_node`."""

    def __init__(self, cfg: SchedulerConfig, seed: int = 0) -> None:
        cap = cfg.netmodel_ring
        n = cfg.max_nodes
        self.cfg = cfg
        self.seed = int(seed)
        self.enabled = cfg.enable_netmodel
        self._lock = threading.RLock()
        self._params = _init_params(cfg, seed)
        self._opt_m = TopoParams(*(jnp.zeros_like(p)
                                   for p in self._params))
        self._opt_v = TopoParams(*(jnp.zeros_like(p)
                                   for p in self._params))
        self._opt_t = jnp.zeros((), jnp.float32)
        self._ema = TopoParams(*(jnp.zeros_like(p)
                                 for p in self._params))
        self._step = jax.jit(_sgd_step)
        self._predict_fn = jax.jit(_predict_dense)

        # Observation ring buffer (host): each probe inserts BOTH
        # directed entries (i, j) and (j, i) so every node trains in
        # both the row-factor and col-factor role (node 0 otherwise
        # only ever appears as ``i`` under upper-triangle probing and
        # its ``v``/``sv`` rows would stay at init).
        self._ring_i = np.zeros((cap,), np.int32)
        self._ring_j = np.zeros((cap,), np.int32)
        self._ring_lat = np.zeros((cap,), np.float32)
        self._ring_y = np.zeros((cap,), np.float32)
        self._ring_wlat = np.zeros((cap,), np.float32)
        self._ring_wbw = np.zeros((cap,), np.float32)
        self._ring_pos = 0
        self._ring_count = 0
        self._batch_rng = np.random.default_rng(seed + 1)

        # Confidence bookkeeping: per-node observation counts, the
        # per-pair clock of the last direct probe (-inf = never), and
        # the per-pair last measured log-bandwidth (NaN = never) for
        # the measurement-vs-measurement degradation channel.
        self._node_obs = np.zeros((n,), np.float32)
        self._last_obs = np.full((n, n), -np.inf, np.float32)
        self._last_y = np.full((n, n), np.nan, np.float32)
        self._clock = 0.0
        self.pairs_observed = 0     # distinct unordered pairs ever probed
        self.steps_total = 0        # SGD steps dispatched
        self.fits_total = 0         # fit() calls that ran >= 1 step
        self._mu_init = False

        # Observed value range: predictions are clipped to it in
        # predict().  The factorization is a completion model, not an
        # extrapolator — without the clip a handful of overshooting
        # pairs (e.g. 120 Gbps against a 50 Gbps fabric) inflate the
        # score normalizer ``bw_max`` and compress every REAL
        # bandwidth difference the placer relies on.
        self._y_lo = np.inf         # min/max observed log1p(bw)
        self._y_hi = -np.inf
        self._lat_hi = 0.0          # max observed latency (ms)

        # Residual monitor: recent |log-space bw residuals| feed the
        # p50/p99 self-metrics; confident sharp divergences become
        # link-degradation records drained by serve.py into Events.
        self._residuals: deque = deque(maxlen=512)
        self._pending_degraded: list[tuple[int, int, float, float, float]] = []
        self.degradations_total = 0

        # Host-side caches: numpy params for per-observation residual
        # checks, and the dense prediction for blend() (recomputed only
        # when the parameter version moves).
        self._np_params: TopoParams | None = None
        self._dense_cache: tuple[np.ndarray, np.ndarray] | None = None
        self._dense_version = -1
        self._version = 0

    # -- ingest -------------------------------------------------------

    def observe(self, i: int, j: int, lat_ms: float | None,
                bw_bps: float | None, t: float) -> None:
        """Ingest one probe measurement between encoder slots ``i`` and
        ``j`` taken at orchestrator clock ``t``."""
        if i == j:
            return
        with self._lock:
            self._clock = max(self._clock, float(t))
            has_lat = lat_ms is not None and np.isfinite(lat_ms) \
                and lat_ms >= 0
            has_bw = bw_bps is not None and np.isfinite(bw_bps) \
                and bw_bps > 0
            if not has_lat and not has_bw:
                return
            if has_bw:
                self._check_residual_locked(i, j, float(bw_bps))
            y = float(np.log1p(bw_bps)) if has_bw else 0.0
            lat = float(lat_ms) if has_lat else 0.0
            if has_bw:
                self._y_lo = min(self._y_lo, y)
                self._y_hi = max(self._y_hi, y)
            if has_lat:
                self._lat_hi = max(self._lat_hi, lat)
            for a, b in ((i, j), (j, i)):
                p = self._ring_pos
                self._ring_i[p] = a
                self._ring_j[p] = b
                self._ring_lat[p] = lat
                self._ring_y[p] = y
                self._ring_wlat[p] = 1.0 if has_lat else 0.0
                self._ring_wbw[p] = 1.0 if has_bw else 0.0
                self._ring_pos = (p + 1) % self._ring_i.shape[0]
                self._ring_count = min(self._ring_count + 1,
                                       self._ring_i.shape[0])
            if not np.isfinite(self._last_obs[i, j]):
                self.pairs_observed += 1
            self._last_obs[i, j] = self._last_obs[j, i] = self._clock
            if has_bw:
                self._last_y[i, j] = self._last_y[j, i] = y
            self._node_obs[i] += 1.0
            self._node_obs[j] += 1.0

    def _check_residual_locked(self, i: int, j: int,
                               bw_bps: float) -> None:
        """Degradation detection, two channels with very different
        evidence quality:

        - a pair measured BEFORE whose new measurement moved more than
          ``netmodel_resid_threshold`` in log space flags on that
          measurement delta alone — the link itself changed, no model
          involved, so no calibration is required;
        - a FIRST measurement can only be judged against the model, so
          it must clear a doubled threshold AND the monitor must be
          calibrated (see :meth:`_calibrated_locked`) — the model's
          error tail on never-probed pairs is exactly where false
          positives live (measured: an ungated monitor emits ~300
          false LinkDegraded events in the first minute on a healthy
          64-node fake cluster).
        """
        y_obs = float(np.log1p(bw_bps))
        prev_y = float(self._last_y[i, j])
        npp = self._np_params
        resid = None
        if npp is not None:
            y_hat = float(npp.mu + npp.su[i] + npp.sv[j]
                          + np.dot(npp.u[i], npp.v[j]))
            resid = abs(y_hat - y_obs)
            self._residuals.append(resid)
        cfg = self.cfg
        if np.isfinite(prev_y):
            if abs(y_obs - prev_y) > cfg.netmodel_resid_threshold:
                self.degradations_total += 1
                self._pending_degraded.append(
                    (int(i), int(j), float(np.expm1(prev_y)), bw_bps,
                     self._clock))
            return
        if resid is None:
            return
        ci = 1.0 - np.exp(-self._node_obs[i] / cfg.netmodel_conf_k)
        cj = 1.0 - np.exp(-self._node_obs[j] / cfg.netmodel_conf_k)
        if ci * cj >= cfg.netmodel_resid_conf \
                and resid > 2.0 * cfg.netmodel_resid_threshold \
                and self._calibrated_locked():
            self.degradations_total += 1
            self._pending_degraded.append(
                (int(i), int(j), float(np.expm1(np.clip(y_hat, 0.0, 60.0))),
                 bw_bps, self._clock))

    def _calibrated_locked(self) -> bool:
        """The model-vs-measurement channel is only a SIGNAL once the
        model's typical error sits well below the divergence
        threshold.  Node-count confidence saturates within a few probe
        cycles — long before the fit is any good — so confidence alone
        cannot gate it.  Median over the recent-residual window,
        demanded under half the flag threshold."""
        if len(self._residuals) < 128:
            return False
        return (float(np.median(self._residuals))
                < 0.5 * self.cfg.netmodel_resid_threshold)

    def advance_clock(self, dt_s: float) -> None:
        with self._lock:
            self._clock += float(dt_s)

    # -- training -----------------------------------------------------

    def fit(self, steps: int | None = None) -> int:
        """Run ``steps`` (default ``cfg.netmodel_steps``) mini-batch
        Adam steps over the ring buffer; returns steps dispatched.

        Every dispatch reuses the ONE compiled step: batch shapes are
        fixed at ``cfg.netmodel_batch`` (indices resampled with
        replacement host-side) and the learning rate is an ordinary
        scalar argument, so there is no per-cycle recompilation.

        The learning rate follows an inverse-sqrt decay in
        ``steps_total`` (halving scale 500 steps).  Constant-lr Adam
        plateaus at its gradient-noise floor — measured at N=1024 /
        3.4% coverage that floor leaves unprobed same-rack pairs with
        median log-residual 0.28 and same-rack-vs-same-zone ranking at
        0.92; the decayed schedule reaches 0.14 / 0.988 on the same
        budget.  The decay is floored at ``netmodel_lr / 8`` so a
        long-running server keeps enough plasticity to track topology
        drift (the residual monitor flags abrupt changes regardless)."""
        cfg = self.cfg
        if steps is None:
            steps = cfg.netmodel_steps
        with self._lock:
            count = self._ring_count
            if count == 0 or steps <= 0:
                return 0
            if not self._mu_init:
                # One-time data-driven init of the global level: log-bw
                # targets sit around 20-24, so starting mu at their
                # mean removes hundreds of warm-up steps.
                wb = self._ring_wbw[:count] > 0
                if wb.any():
                    mu0 = float(np.mean(self._ring_y[:count][wb]))
                    self._params = self._params._replace(
                        mu=jnp.asarray(mu0, jnp.float32))
                    self._mu_init = True
            params, m, v, t, ema = (self._params, self._opt_m,
                                    self._opt_v, self._opt_t, self._ema)
            lr = max(cfg.netmodel_lr
                     / float(np.sqrt(1.0 + self.steps_total / 500.0)),
                     cfg.netmodel_lr / 8.0)
            for _ in range(steps):
                idx = self._batch_rng.integers(0, count,
                                               size=cfg.netmodel_batch)
                params, m, v, t, ema = self._step(
                    params, m, v, t, ema,
                    self._ring_i[idx], self._ring_j[idx],
                    self._ring_lat[idx], self._ring_y[idx],
                    self._ring_wlat[idx], self._ring_wbw[idx], lr)
            self._params = params
            self._opt_m, self._opt_v, self._opt_t = m, v, t
            self._ema = ema
            self.steps_total += steps
            self.fits_total += 1
            self._version += 1
            self._refresh_np_locked()
        return steps

    def _eval_params_locked(self) -> TopoParams:
        """Bias-corrected prediction parameters: the EMA of the Adam
        iterates (see ``_EMA_DECAY``), or the raw parameters before
        the first step."""
        t = float(self._opt_t)
        if t <= 0:
            return self._params
        c = 1.0 - _EMA_DECAY ** t
        return TopoParams(*(e / c for e in self._ema))

    def _refresh_np_locked(self) -> None:
        self._np_params = TopoParams(
            *(np.asarray(p) for p in self._eval_params_locked()))

    # -- prediction / blending ---------------------------------------

    def predict(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Dense host-side ``(lat_hat, bw_hat, confidence)[N, N]``.
        The dense matrices are cached per parameter version (a snapshot
        with no intervening fit() pays no device work)."""
        with self._lock:
            if self._dense_version != self._version:
                lat_hat, bw_hat = self._predict_fn(
                    self._eval_params_locked())
                lat_hat = np.asarray(lat_hat)
                bw_hat = np.asarray(bw_hat)
                # Clip to the observed range: completion, not
                # extrapolation (see __init__ — unclipped overshoot
                # poisons the score normalizers downstream).
                if np.isfinite(self._y_hi):
                    bw_hat = np.clip(bw_hat, float(np.expm1(self._y_lo)),
                                     float(np.expm1(self._y_hi)))
                if self._lat_hi > 0.0:
                    lat_hat = np.clip(lat_hat, 0.0, self._lat_hi)
                self._dense_cache = (lat_hat, bw_hat)
                self._dense_version = self._version
            lat_hat, bw_hat = self._dense_cache
            return lat_hat, bw_hat, self._confidence_locked()

    def _confidence_locked(self) -> np.ndarray:
        c = 1.0 - np.exp(-self._node_obs / self.cfg.netmodel_conf_k)
        return (c[:, None] * c[None, :]).astype(np.float32)

    def blend(self, lat_probe: np.ndarray, bw_probe: np.ndarray
              ) -> tuple[np.ndarray, np.ndarray]:
        """Confidence-weighted merge of probe matrices and model
        predictions.

        Per pair: weight ``w_p = exp(-age/tau)`` for the direct probe
        (0 where never probed) and ``w_m = (1 - w_p) * confidence`` for
        the model; where both vanish (never probed AND unknown nodes)
        the raw probe value is kept, so a disabled-or-ignorant model
        can only ever fall back to today's behavior.  The diagonal is
        always the probe layer's (loopback semantics are not the
        model's to learn)."""
        lat_hat, bw_hat, conf = self.predict()
        with self._lock:
            age = self._clock - self._last_obs  # +inf where never
            w_p = np.exp(-np.maximum(age, 0.0)
                         / self.cfg.netmodel_tau_s).astype(np.float32)
        w_m = (1.0 - w_p) * conf
        denom = w_p + w_m
        safe = denom > 1e-9
        denom = np.where(safe, denom, 1.0)
        lat = np.where(safe, (w_p * lat_probe + w_m * lat_hat) / denom,
                       lat_probe)
        bw = np.where(safe, (w_p * bw_probe + w_m * bw_hat) / denom,
                      bw_probe)
        np.fill_diagonal(lat, np.diag(lat_probe))
        np.fill_diagonal(bw, np.diag(bw_probe))
        return lat.astype(np.float32), bw.astype(np.float32)

    # -- observability ------------------------------------------------

    def coverage_fraction(self, num_active: int) -> float:
        """Fraction of unordered active-node pairs ever directly
        probed."""
        total = num_active * (num_active - 1) // 2
        if total <= 0:
            return 0.0
        return min(1.0, self.pairs_observed / total)

    def residual_quantiles(self) -> tuple[float, float]:
        """(p50, p99) of recent |log-space bandwidth residuals|
        (NaN, NaN before any confident observation)."""
        with self._lock:
            if not self._residuals:
                return float("nan"), float("nan")
            arr = np.asarray(self._residuals, dtype=np.float64)
        return (float(np.quantile(arr, 0.5)),
                float(np.quantile(arr, 0.99)))

    def drain_degradations(self) -> list[tuple[int, int, float, float,
                                               float]]:
        """Pop pending link-degradation records:
        ``(i, j, predicted_bps, measured_bps, clock)``."""
        with self._lock:
            out, self._pending_degraded = self._pending_degraded, []
            return out

    # -- lifecycle ----------------------------------------------------

    def reset_node(self, idx: int) -> None:
        """A node slot was removed/reused: forget its observations and
        re-initialize its parameter rows (deterministically from the
        model seed + slot, so restored replicas agree)."""
        with self._lock:
            self._node_obs[idx] = 0.0
            self._last_obs[idx, :] = -np.inf
            self._last_obs[:, idx] = -np.inf
            self._last_y[idx, :] = np.nan
            self._last_y[:, idx] = np.nan
            self.pairs_observed = int(
                np.isfinite(self._last_obs).sum() // 2)
            rng = np.random.default_rng(self.seed * 1_000_003 + idx)
            d, r = self.cfg.netmodel_dim, self.cfg.netmodel_rank
            p = self._params
            self._params = p._replace(
                x=p.x.at[idx].set(jnp.asarray(
                    0.1 * rng.standard_normal(d).astype(np.float32))),
                h=p.h.at[idx].set(0.0),
                u=p.u.at[idx].set(jnp.asarray(
                    (rng.standard_normal(r) / np.sqrt(r)).astype(np.float32))),
                v=p.v.at[idx].set(jnp.asarray(
                    (rng.standard_normal(r) / np.sqrt(r)).astype(np.float32))),
                su=p.su.at[idx].set(0.0),
                sv=p.sv.at[idx].set(0.0),
            )
            for attr in ("_opt_m", "_opt_v", "_ema"):
                a = getattr(self, attr)
                setattr(self, attr, a._replace(
                    x=a.x.at[idx].set(0.0), h=a.h.at[idx].set(0.0),
                    u=a.u.at[idx].set(0.0), v=a.v.at[idx].set(0.0),
                    su=a.su.at[idx].set(0.0), sv=a.sv.at[idx].set(0.0),
                ))
            self._version += 1
            self._refresh_np_locked()

    # -- persistence --------------------------------------------------

    def save(self, path: str) -> None:
        """Atomically persist parameters + confidence state + ring
        buffer to a single ``.npz`` (restarts resume learning instead
        of starting from scratch; save -> load -> predict is exact)."""
        with self._lock:
            arrays = {f"param_{name}": np.asarray(val)
                      for name, val in zip(TopoParams._fields,
                                           self._params)}
            arrays.update({f"opt_m_{name}": np.asarray(val)
                           for name, val in zip(TopoParams._fields,
                                                self._opt_m)})
            arrays.update({f"opt_v_{name}": np.asarray(val)
                           for name, val in zip(TopoParams._fields,
                                                self._opt_v)})
            arrays["opt_t"] = np.asarray(self._opt_t)
            arrays.update({f"ema_{name}": np.asarray(val)
                           for name, val in zip(TopoParams._fields,
                                                self._ema)})
            arrays.update(
                node_obs=self._node_obs.copy(),
                last_obs=self._last_obs.copy(),
                last_y=self._last_y.copy(),
                ring_i=self._ring_i.copy(), ring_j=self._ring_j.copy(),
                ring_lat=self._ring_lat.copy(),
                ring_y=self._ring_y.copy(),
                ring_wlat=self._ring_wlat.copy(),
                ring_wbw=self._ring_wbw.copy(),
                scalars=np.asarray(
                    [self._clock, self._ring_pos, self._ring_count,
                     1.0 if self._mu_init else 0.0,
                     self.steps_total, self.pairs_observed,
                     self.degradations_total,
                     self._y_lo, self._y_hi, self._lat_hi],
                    np.float64))
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str, cfg: SchedulerConfig,
             seed: int = 0) -> "TopologyModel":
        model = cls(cfg, seed=seed)
        with np.load(path) as data:
            params = []
            for name, init in zip(TopoParams._fields, model._params):
                stored = data[f"param_{name}"]
                if stored.shape != init.shape:
                    raise ValueError(
                        f"netmodel checkpoint param {name} has shape "
                        f"{stored.shape}, config expects {init.shape} "
                        "(dims/rank/max_nodes changed — start fresh)")
                params.append(jnp.asarray(stored))
            model._params = TopoParams(*params)
            if "opt_m_x" in data:
                model._opt_m = TopoParams(
                    *(jnp.asarray(data[f"opt_m_{name}"])
                      for name in TopoParams._fields))
                model._opt_v = TopoParams(
                    *(jnp.asarray(data[f"opt_v_{name}"])
                      for name in TopoParams._fields))
                model._opt_t = jnp.asarray(data["opt_t"])
            if "ema_x" in data:
                model._ema = TopoParams(
                    *(jnp.asarray(data[f"ema_{name}"])
                      for name in TopoParams._fields))
            model._node_obs = data["node_obs"].astype(np.float32)
            model._last_obs = data["last_obs"].astype(np.float32)
            if "last_y" in data:
                model._last_y = data["last_y"].astype(np.float32)
            for ring in ("ring_i", "ring_j", "ring_lat", "ring_y",
                         "ring_wlat", "ring_wbw"):
                stored = data[ring]
                target = getattr(model, f"_{ring}")
                if stored.shape != target.shape:
                    raise ValueError(
                        f"netmodel checkpoint {ring} has shape "
                        f"{stored.shape}, config ring is {target.shape}")
                target[...] = stored
            sc = data["scalars"]
            model._clock = float(sc[0])
            model._ring_pos = int(sc[1])
            model._ring_count = int(sc[2])
            model._mu_init = bool(sc[3])
            model.steps_total = int(sc[4])
            model.pairs_observed = int(sc[5])
            model.degradations_total = int(sc[6])
            if len(sc) >= 10:
                model._y_lo = float(sc[7])
                model._y_hi = float(sc[8])
                model._lat_hi = float(sc[9])
        model._version += 1
        model._refresh_np_locked()
        return model
