"""Expected-information-gain probe planning.

Stalest-pair-first probing (the orchestrator's default) spends the
budget uniformly over the pair space: at 5k nodes every pair gets
re-probed every ~54 hours whether the model is certain about it or
not.  Once a :class:`~.model.TopologyModel` is learning the topology,
the budget is better spent where a measurement changes the most
beliefs: pairs whose ENDPOINTS the model is uncertain about, weighted
by how much placement actually cares about those nodes.

The planner scores every candidate pair as::

    EIG(i, j) ~ age_factor(i, j) * (uncert(i) + uncert(j))
                * sqrt(relevance(i) * relevance(j))

- ``age_factor = 1 - exp(-age / tau)`` — a just-probed pair carries no
  new information; never-probed pairs saturate at 1.
- ``uncert = 1 / (1 + n_obs / conf_k)`` — the complement of the
  model's per-node confidence saturation; a node with many
  observations pins its coordinates/factors, so further probes on it
  are low-gain.
- ``relevance`` — an EMA of placement activity per node
  (:meth:`note_placements`), defaulting to uniform: probing decides
  placements, so nodes that actually receive pods deserve sharper
  estimates.

A configurable ``explore_frac`` share of every budget still goes to
pure stalest-first selection — the model's uncertainty estimate is
itself learned, and a persistently-wrong confident region would
otherwise never be re-measured (the classic active-learning echo
chamber).

The planner also reports the Shannon entropy of each cycle's selected
score distribution (``last_entropy_bits``) — collapsing entropy means
the planner is fixating on few pairs, a tuning signal exported via
self-metrics.
"""

from __future__ import annotations

import numpy as np

from kubernetesnetawarescheduler_tpu.netmodel.model import TopologyModel


class EIGProbePlanner:
    """Uncertainty x placement-relevance pair selection for the
    :class:`~..ingest.probe.ProbeOrchestrator` (its ``planner=`` hook).
    """

    def __init__(self, model: TopologyModel, explore_frac: float = 0.25,
                 relevance_decay: float = 0.99, seed: int = 0) -> None:
        if not 0.0 <= explore_frac <= 1.0:
            raise ValueError("explore_frac must be in [0, 1]")
        self._model = model
        self._explore_frac = float(explore_frac)
        self._decay = float(relevance_decay)
        self._relevance = np.ones((model.cfg.max_nodes,), np.float32)
        self.last_entropy_bits = 0.0
        self.selections_total = 0

    def note_placements(self, node_indices) -> None:
        """Feed placement activity (encoder node slots of fresh binds);
        bumps those nodes' relevance EMA."""
        idx = np.asarray(list(node_indices), np.int64)
        if idx.size == 0:
            return
        self._relevance *= self._decay
        np.add.at(self._relevance, idx, 1.0)

    def select_pairs(self, n: int, budget: int,
                     stalest_fn) -> list[tuple[int, int]]:
        """Pick ``budget`` index pairs among the first ``n`` nodes.
        ``stalest_fn(k)`` is the orchestrator's stalest-first selector,
        used for the exploration share."""
        if budget <= 0 or n < 2:
            return []
        k_explore = min(budget, int(round(self._explore_frac * budget)))
        explore = [tuple(p) for p in stalest_fn(k_explore)] \
            if k_explore else []
        k_exploit = budget - len(explore)
        if k_exploit <= 0:
            self.selections_total += len(explore)
            return explore

        m = self._model
        cfg = m.cfg
        with m._lock:
            node_obs = m._node_obs[:n].copy()
            age = m._clock - m._last_obs[:n, :n]
        uncert = 1.0 / (1.0 + node_obs / cfg.netmodel_conf_k)
        rel = np.sqrt(np.outer(self._relevance[:n],
                               self._relevance[:n]))
        age_f = 1.0 - np.exp(
            -np.clip(age, 0.0, 1e12) / cfg.netmodel_tau_s)
        score = age_f * (uncert[:, None] + uncert[None, :]) * rel

        iu, ju = np.triu_indices(n, 1)
        flat = score[iu, ju]
        taken = set(explore)
        # Over-select so dropping the exploration duplicates still
        # leaves a full budget, then trim.
        k = min(flat.size, k_exploit + len(taken))
        top = np.argpartition(flat, flat.size - k)[flat.size - k:]
        top = top[np.argsort(flat[top])[::-1]]
        exploit: list[tuple[int, int]] = []
        chosen_scores: list[float] = []
        for t in top:
            pair = (int(iu[t]), int(ju[t]))
            if pair in taken:
                continue
            exploit.append(pair)
            chosen_scores.append(float(flat[t]))
            if len(exploit) >= k_exploit:
                break

        total = np.asarray(chosen_scores, np.float64)
        mass = float(total.sum())
        if total.size and mass > 0:
            p = total / mass
            nz = p > 0
            self.last_entropy_bits = float(
                -(p[nz] * np.log2(p[nz])).sum())
        else:
            self.last_entropy_bits = 0.0
        self.selections_total += len(explore) + len(exploit)
        return explore + exploit
