"""Learned network-topology model (coordinate embedding + low-rank
bandwidth completion) over the budgeted probe stream.

See :mod:`.model` for the estimator and :mod:`.planner` for the
expected-information-gain probe planner."""

from kubernetesnetawarescheduler_tpu.netmodel.model import (
    TopoParams,
    TopologyModel,
)
from kubernetesnetawarescheduler_tpu.netmodel.planner import (
    EIGProbePlanner,
)

__all__ = ("TopoParams", "TopologyModel", "EIGProbePlanner")
