"""Log-bucketed latency histograms: exact counts + windowed percentiles.

Before r11, every latency-shaped series the repo exported lived in one
of two shapes: the PhaseTimer's bounded ``(seconds, count)`` window
(percentiles only over the retained window, no distribution export) or
an ad-hoc ``deque`` on the loop (``_static_refresh_ms``,
``_staleness_samples``, ``round_samples``) that /metrics summarized
with ``np.quantile`` at scrape time.  Neither can answer "how many
cycles ever crossed 5 ms" after the window slides, and neither exports
a shape Prometheus can aggregate across replicas (quantiles don't sum;
histogram buckets do).

:class:`LogHistogram` is the replacement: HDR-style geometric buckets
(``growth``× per bucket, so relative error is bounded by the growth
factor) with EXACT running ``count``/``sum`` that never evict, plus a
bounded sample window for exact p50/p99 over recent observations —
the same split PhaseTimer made in r6.  One lock, snapshot-then-math
like ``PhaseTimer._snapshot``: a /metrics scrape never holds the lock
through sorting or string formatting.

It is also a drop-in for the ad-hoc deques it replaces: ``append`` /
``extend`` / ``clear`` / ``len()`` / iteration / ``[-1]`` all work on
the sample window, so existing consumers (bench/density's
``np.percentile(list(...))``, tests asserting ``len(...)``) keep
working while the bucket counts accrue underneath.

:func:`prom_histogram_lines` renders a snapshot as a native Prometheus
histogram (cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``).
Only buckets that received observations are emitted (plus ``+Inf``) —
cumulative ``le`` series stay valid under any subset of bounds, and
the scrape stays small.

:class:`HistogramPhaseTimer` subclasses PhaseTimer so every
``record()`` also lands in a per-phase LogHistogram: the existing
``netaware_phase_latency_seconds`` summary keeps its series while
``..._hist`` native histograms ride along (ISSUE 11 satellite: migrate
without renaming).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Iterator

from kubernetesnetawarescheduler_tpu.utils.tracing import (
    PhaseTimer,
    _weighted_percentile,
)

__all__ = [
    "HistogramPhaseTimer",
    "LogHistogram",
    "prom_histogram_lines",
]

#: Default percentile-window retention — matches PhaseTimer's bound.
DEFAULT_WINDOW = 8192


def _geometric_bounds(lo: float, hi: float, growth: float
                      ) -> tuple[float, ...]:
    """Bucket upper bounds ``lo, lo*growth, ...`` up to (and covering)
    ``hi``.  The last finite bound is >= hi; values above it land in
    the implicit +Inf bucket."""
    if not (lo > 0.0 and hi > lo and growth > 1.0):
        raise ValueError(
            f"need 0 < lo < hi and growth > 1, got lo={lo} hi={hi} "
            f"growth={growth}")
    n = max(1, math.ceil(math.log(hi / lo) / math.log(growth)))
    return tuple(lo * growth ** i for i in range(n + 1))


class LogHistogram:
    """Geometric-bucket histogram + bounded exact-sample window.

    Thread-safe; every mutation and snapshot is one lock acquisition.
    Unit-agnostic: callers pick bounds in whatever unit they record
    (the loop's refresh histogram records milliseconds, the phase
    histograms seconds)."""

    __slots__ = ("_bounds", "_buckets", "_overflow", "_count", "_sum",
                 "_window", "_maxlen", "_lock")

    def __init__(self, lo: float = 1e-5, hi: float = 1e3,
                 growth: float = math.sqrt(2.0),
                 window: int = DEFAULT_WINDOW) -> None:
        self._bounds = _geometric_bounds(lo, hi, growth)
        self._buckets = [0] * len(self._bounds)
        self._overflow = 0          # observations above the last bound
        self._count = 0             # exact, never evicts
        self._sum = 0.0             # exact, never evicts
        # (value, count) pairs, newest last; bounded like PhaseTimer's
        # per-phase deque but stored as a list ring to keep __slots__
        # simple (evictions pop from the front in O(k) amortized).
        self._window: list[tuple[float, int]] = []
        self._maxlen = max(1, int(window))
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------

    def record(self, value: float, count: int = 1) -> None:
        if count < 1:
            return
        value = float(value)
        # <= bound semantics (Prometheus ``le``): bisect_left on the
        # bounds finds the first bound >= value.
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            if idx >= len(self._bounds):
                self._overflow += count
            else:
                self._buckets[idx] += count
            self._count += count
            self._sum += value * count
            self._window.append((value, count))
            if len(self._window) > self._maxlen:
                del self._window[0:len(self._window) - self._maxlen]

    # Deque drop-in surface (the ad-hoc deques this class replaces
    # were appended/extended with bare floats, listed, len()'d,
    # cleared and indexed with [-1]).

    def append(self, value: float) -> None:
        self.record(value)

    def extend(self, values) -> None:
        for v in values:
            self.record(v)

    def clear(self) -> None:
        """Reset everything — window AND exact aggregates (bench warmup
        windows use this to exclude compile time, which must not leak
        into the exported distribution either)."""
        with self._lock:
            self._buckets = [0] * len(self._bounds)
            self._overflow = 0
            self._count = 0
            self._sum = 0.0
            self._window.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._window)

    def __iter__(self) -> Iterator[float]:
        with self._lock:
            window = list(self._window)
        for value, count in window:
            for _ in range(count):
                yield value

    def __getitem__(self, idx: int) -> float:
        with self._lock:
            return self._window[idx][0]

    # -- reading -----------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile (q in [0, 100]) over the retained
        window — exact over recent observations, same contract as
        ``PhaseTimer.percentile``.  Sort happens outside the lock."""
        with self._lock:
            window = list(self._window)
        return _weighted_percentile(window, q)

    def snapshot(self) -> dict[str, Any]:
        """One-lock consistent copy: exact aggregates, CUMULATIVE
        bucket counts (``le`` upper-bound keyed, Prometheus shape) and
        the percentile window.  All derivation (cumsum) runs on the
        copy, outside the lock."""
        with self._lock:
            buckets = list(self._buckets)
            overflow = self._overflow
            count = self._count
            total = self._sum
            window = list(self._window)
        cum = 0
        cumulative: list[tuple[float, int]] = []
        for bound, c in zip(self._bounds, buckets):
            cum += c
            cumulative.append((bound, cum))
        return {
            "count": count,
            "sum": total,
            "buckets": cumulative,          # [(le, cumulative_count)]
            "overflow": overflow,
            "window": window,
            "p50": _weighted_percentile(list(window), 50),
            "p99": _weighted_percentile(list(window), 99),
        }


def prom_histogram_lines(name: str, help_: str, snap: dict[str, Any],
                         labels: str = "",
                         header: bool = True) -> list[str]:
    """Render a :meth:`LogHistogram.snapshot` as native Prometheus
    histogram exposition lines.  ``labels`` (e.g. ``phase="encode"``)
    is spliced into every series; a family with several label sets
    emits the HELP/TYPE header with the first set only
    (``header=False`` for the rest — duplicate headers are invalid
    exposition).

    Sparse: only buckets whose cumulative count advanced are emitted,
    plus the mandatory ``+Inf`` — valid cumulative-``le`` output that
    keeps a 50-bucket family from dominating the scrape."""
    sep = "," if labels else ""
    out = ([f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
           if header else [])
    prev = 0
    for le, cum in snap["buckets"]:
        if cum != prev:
            out.append(
                f'{name}_bucket{{{labels}{sep}le="{le:.6g}"}} {cum}')
            prev = cum
    out.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} '
               f'{snap["count"]}')
    lab = f"{{{labels}}}" if labels else ""
    out.append(f"{name}_sum{lab} {snap['sum']:.9g}")
    out.append(f"{name}_count{lab} {snap['count']}")
    return out


class HistogramPhaseTimer(PhaseTimer):
    """PhaseTimer whose every ``record()`` also lands in a per-phase
    :class:`LogHistogram` — the migration seam for the existing
    ``netaware_phase_latency_seconds`` summary family: the summary
    keeps rendering from the PhaseTimer window (series names
    unchanged) while ``/metrics`` gains native ``_hist`` buckets from
    the same observations.  Phase latencies span ~10 us (null phases)
    to tens of seconds (cold compiles): bounds 1e-5 s .. 1e3 s at
    sqrt(2) growth = 54 buckets, <=41% relative bucket error."""

    def __init__(self, max_samples: int | None = None) -> None:
        if max_samples is None:
            super().__init__()
        else:
            super().__init__(max_samples)
        self.hists: dict[str, LogHistogram] = {}
        self._hist_lock = threading.Lock()

    def record(self, name: str, seconds: float,
               count: int = 1) -> None:
        super().record(name, seconds, count)
        if count < 1:
            return
        h = self.hists.get(name)
        if h is None:
            with self._hist_lock:
                h = self.hists.setdefault(
                    name, LogHistogram(lo=1e-5, hi=1e3,
                                       growth=math.sqrt(2.0)))
        h.record(seconds, count)

    def reset(self) -> None:
        super().reset()
        with self._hist_lock:
            self.hists.clear()
