"""Self-metrics: per-phase latency histograms and throughput counters.

The reference had no observability of itself at all — only ``println``
debugging of scraped values (scheduler.go:517, :525-526) and a node-name
log line (scheduler.go:182).  Here per-phase (encode / score / assign /
bind) timings and percentiles are first-class, because the north-star
target is expressed as one (p99 Score() < 5 ms, BASELINE.json).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Mapping


class PhaseTimer:
    """Accumulates wall-clock samples per named phase.

    Thread-safe: the serving cycle, the async bind worker and the
    /metrics scrape thread all touch one timer — an unsynchronized
    first ``phase()`` from the worker would insert a dict key mid-
    ``summary()`` iteration on the scrape thread."""

    def __init__(self) -> None:
        # (seconds, weight) pairs: a burst cycle records its
        # per-batch-normalized sample once with weight n_batches
        # instead of n_batches identical floats, so storage stays
        # O(cycles) in a long-lived daemon while the percentile math
        # still gives each batch full weight.
        self._samples: dict[str, list[tuple[float, int]]] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - start)

    def record(self, name: str, seconds: float,
               count: int = 1) -> None:
        """Record ``count`` observations of ``seconds`` (weighted)."""
        if count < 1:
            return
        with self._lock:
            self._samples.setdefault(name, []).append((seconds, count))

    def count(self, name: str) -> int:
        with self._lock:
            return sum(c for _, c in self._samples.get(name, ()))

    def total(self, name: str) -> float:
        with self._lock:
            return sum(s * c for s, c in self._samples.get(name, ()))

    def percentile(self, name: str, q: float) -> float:
        """q in [0, 100]; nearest-rank on the weight-expanded sorted
        samples (identical to materializing each pair ``count``
        times)."""
        with self._lock:
            samples = sorted(self._samples.get(name, ()))
        if not samples:
            return 0.0
        n = sum(c for _, c in samples)
        rank = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
        cum = 0
        for value, c in samples:
            cum += c
            if rank < cum:
                return value
        return samples[-1][0]

    def summary(self) -> Mapping[str, Mapping[str, float]]:
        with self._lock:
            names = list(self._samples)
        out: dict[str, dict[str, float]] = {}
        for name in names:
            out[name] = {
                "count": float(self.count(name)),
                "total_s": self.total(name),
                "p50_ms": self.percentile(name, 50) * 1e3,
                "p99_ms": self.percentile(name, 99) * 1e3,
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
