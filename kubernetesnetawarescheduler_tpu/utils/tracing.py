"""Self-metrics: per-phase latency histograms and throughput counters.

The reference had no observability of itself at all — only ``println``
debugging of scraped values (scheduler.go:517, :525-526) and a node-name
log line (scheduler.go:182).  Here per-phase (encode / score / assign /
bind) timings and percentiles are first-class, because the north-star
target is expressed as one (p99 Score() < 5 ms, BASELINE.json).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Iterator, Mapping

# Per-phase retention ceiling for the percentile window.  The 25-minute
# soak (soak.json r5) accumulated 208,210 timer entries — 28.5 MB of RSS
# growth — because the sample list was O(cycles).  Percentiles only need
# a recent window; counts and totals are kept as exact running
# aggregates that never evict.  8192 weighted pairs cover >2h of serving
# cycles at the soak's wave rate while bounding each phase to ~200 KB.
MAX_SAMPLES_PER_PHASE = 8192


class PhaseTimer:
    """Accumulates wall-clock samples per named phase.

    Thread-safe: the serving cycle, the async bind worker and the
    /metrics scrape thread all touch one timer — an unsynchronized
    first ``phase()`` from the worker would insert a dict key mid-
    ``summary()`` iteration on the scrape thread.

    Memory-bounded: each phase retains at most
    ``MAX_SAMPLES_PER_PHASE`` weighted ``(seconds, count)`` pairs for
    percentile queries (a sliding window over the most recent
    observations); ``count()`` and ``total()`` are exact running
    aggregates unaffected by eviction."""

    def __init__(self,
                 max_samples: int = MAX_SAMPLES_PER_PHASE) -> None:
        # (seconds, weight) pairs: a burst cycle records its
        # per-batch-normalized sample once with weight n_batches
        # instead of n_batches identical floats, so the window holds
        # cycles, not pods, while the percentile math still gives each
        # batch full weight.
        self.max_samples = int(max_samples)
        self._samples: dict[str, Deque[tuple[float, int]]] = {}
        self._counts: dict[str, int] = {}
        self._totals: dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - start)

    def record(self, name: str, seconds: float,
               count: int = 1) -> None:
        """Record ``count`` observations of ``seconds`` (weighted)."""
        if count < 1:
            return
        with self._lock:
            buf = self._samples.get(name)
            if buf is None:
                buf = collections.deque(maxlen=self.max_samples)
                self._samples[name] = buf
            buf.append((seconds, count))
            self._counts[name] = self._counts.get(name, 0) + count
            self._totals[name] = (self._totals.get(name, 0.0)
                                  + seconds * count)

    def count(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def total(self, name: str) -> float:
        with self._lock:
            return self._totals.get(name, 0.0)

    def samples_len(self, name: str) -> int:
        """Retained (seconds, count) pairs in the percentile window —
        bounded by ``max_samples`` regardless of record() volume."""
        with self._lock:
            return len(self._samples.get(name, ()))

    def percentile(self, name: str, q: float) -> float:
        """q in [0, 100]; nearest-rank on the weight-expanded sorted
        samples (identical to materializing each pair ``count``
        times).  Computed over the retained window — the most recent
        ``max_samples`` weighted pairs."""
        with self._lock:
            samples = sorted(self._samples.get(name, ()))
        if not samples:
            return 0.0
        n = sum(c for _, c in samples)
        rank = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
        cum = 0
        for value, c in samples:
            cum += c
            if rank < cum:
                return value
        return samples[-1][0]

    def summary(self) -> Mapping[str, Mapping[str, float]]:
        with self._lock:
            names = list(self._samples)
        out: dict[str, dict[str, float]] = {}
        for name in names:
            out[name] = {
                "count": float(self.count(name)),
                "total_s": self.total(name),
                "p50_ms": self.percentile(name, 50) * 1e3,
                "p99_ms": self.percentile(name, 99) * 1e3,
            }
        return out

    def pipeline_budgets(self, phases: Mapping[str, str] | None = None,
                         ) -> dict[str, dict[str, float]]:
        """Per-stage budget block for bench artifacts: for each pipeline
        stage (encode / dispatch / bind by default) report mean, p50,
        p99 in ms plus the total seconds, so artifacts carry the
        overlap structure on their face."""
        if phases is None:
            # encode: host array prep (overlaps the device step in
            # pipelined mode); dispatch: host-side launch cost
            # (finalize+snapshot+trace, pipelined mode only);
            # device_wait: the blocking fetch — in pipelined mode only
            # the NON-overlapped residue of the device step; bind: the
            # network fanout on the async-bind worker.
            phases = {"encode": "encode", "dispatch": "dispatch",
                      "device_wait": "score_assign",
                      "bind": "bind_net"}
        out: dict[str, dict[str, float]] = {}
        for stage, name in phases.items():
            c = self.count(name)
            if not c:
                continue
            tot = self.total(name)
            out[stage] = {
                "count": float(c),
                "mean_ms": round(tot / c * 1e3, 3),
                "p50_ms": round(self.percentile(name, 50) * 1e3, 3),
                "p99_ms": round(self.percentile(name, 99) * 1e3, 3),
                "total_s": round(tot, 3),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._counts.clear()
            self._totals.clear()
