"""Self-metrics: per-phase latency histograms and throughput counters.

The reference had no observability of itself at all — only ``println``
debugging of scraped values (scheduler.go:517, :525-526) and a node-name
log line (scheduler.go:182).  Here per-phase (encode / score / assign /
bind) timings and percentiles are first-class, because the north-star
target is expressed as one (p99 Score() < 5 ms, BASELINE.json).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Iterator, Mapping

# Per-phase retention ceiling for the percentile window.  The 25-minute
# soak (soak.json r5) accumulated 208,210 timer entries — 28.5 MB of RSS
# growth — because the sample list was O(cycles).  Percentiles only need
# a recent window; counts and totals are kept as exact running
# aggregates that never evict.  8192 weighted pairs cover >2h of serving
# cycles at the soak's wave rate while bounding each phase to ~200 KB.
MAX_SAMPLES_PER_PHASE = 8192


def _weighted_percentile(samples: list[tuple[float, int]],
                         q: float) -> float:
    """Nearest-rank percentile over weight-expanded ``(seconds, count)``
    pairs — identical to materializing each pair ``count`` times.  Pure
    (no lock): callers pass an already-snapshotted list; the sort
    happens here, outside any lock."""
    if not samples:
        return 0.0
    samples = sorted(samples)
    n = sum(c for _, c in samples)
    rank = min(n - 1, max(0, int(round(q / 100.0 * (n - 1)))))
    cum = 0
    for value, c in samples:
        cum += c
        if rank < cum:
            return value
    return samples[-1][0]


class PhaseTimer:
    """Accumulates wall-clock samples per named phase.

    Thread-safe: the serving cycle, the async bind worker and the
    /metrics scrape thread all touch one timer — an unsynchronized
    first ``phase()`` from the worker would insert a dict key mid-
    ``summary()`` iteration on the scrape thread.

    Memory-bounded: each phase retains at most
    ``MAX_SAMPLES_PER_PHASE`` weighted ``(seconds, count)`` pairs for
    percentile queries (a sliding window over the most recent
    observations); ``count()`` and ``total()`` are exact running
    aggregates unaffected by eviction."""

    def __init__(self,
                 max_samples: int = MAX_SAMPLES_PER_PHASE) -> None:
        # (seconds, weight) pairs: a burst cycle records its
        # per-batch-normalized sample once with weight n_batches
        # instead of n_batches identical floats, so the window holds
        # cycles, not pods, while the percentile math still gives each
        # batch full weight.
        self.max_samples = int(max_samples)
        self._samples: dict[str, Deque[tuple[float, int]]] = {}
        self._counts: dict[str, int] = {}
        self._totals: dict[str, float] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - start)

    def record(self, name: str, seconds: float,
               count: int = 1) -> None:
        """Record ``count`` observations of ``seconds`` (weighted)."""
        if count < 1:
            return
        with self._lock:
            buf = self._samples.get(name)
            if buf is None:
                buf = collections.deque(maxlen=self.max_samples)
                self._samples[name] = buf
            buf.append((seconds, count))
            self._counts[name] = self._counts.get(name, 0) + count
            self._totals[name] = (self._totals.get(name, 0.0)
                                  + seconds * count)

    def count(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def total(self, name: str) -> float:
        with self._lock:
            return self._totals.get(name, 0.0)

    def samples_len(self, name: str) -> int:
        """Retained (seconds, count) pairs in the percentile window —
        bounded by ``max_samples`` regardless of record() volume."""
        with self._lock:
            return len(self._samples.get(name, ()))

    def percentile(self, name: str, q: float) -> float:
        """q in [0, 100]; nearest-rank on the weight-expanded sorted
        samples (identical to materializing each pair ``count``
        times).  Computed over the retained window — the most recent
        ``max_samples`` weighted pairs.  The lock only covers the
        snapshot copy; the O(n log n) sort runs outside it so a scrape
        never stalls the serving thread's ``record()``."""
        with self._lock:
            samples = list(self._samples.get(name, ()))
        return _weighted_percentile(samples, q)

    def _snapshot(self) -> tuple[dict[str, list[tuple[float, int]]],
                                 dict[str, int], dict[str, float]]:
        """One consistent copy of (samples, counts, totals) under a
        single lock acquisition — the scrape path's entire critical
        section."""
        with self._lock:
            samples = {name: list(buf)
                       for name, buf in self._samples.items()}
            counts = dict(self._counts)
            totals = dict(self._totals)
        return samples, counts, totals

    def summary(self) -> Mapping[str, Mapping[str, float]]:
        """One lock acquisition total (via :meth:`_snapshot`), then all
        sorting and percentile math on the copies — previously this
        re-took the lock 3×+ per phase and sorted inside it."""
        samples, counts, totals = self._snapshot()
        out: dict[str, dict[str, float]] = {}
        for name, buf in samples.items():
            out[name] = {
                "count": float(counts.get(name, 0)),
                "total_s": totals.get(name, 0.0),
                "p50_ms": _weighted_percentile(buf, 50) * 1e3,
                "p99_ms": _weighted_percentile(buf, 99) * 1e3,
            }
        return out

    def pipeline_budgets(self, phases: Mapping[str, str] | None = None,
                         ) -> dict[str, dict[str, float]]:
        """Per-stage budget block for bench artifacts: for each pipeline
        stage (encode / dispatch / bind by default) report mean, p50,
        p99 in ms plus the total seconds, so artifacts carry the
        overlap structure on their face."""
        if phases is None:
            # encode: host array prep (overlaps the device step in
            # pipelined mode); dispatch: host-side launch cost
            # (finalize+snapshot+trace, pipelined mode only);
            # device_wait: the blocking fetch — in pipelined mode only
            # the NON-overlapped residue of the device step; bind: the
            # network fanout on the async-bind worker.
            phases = {"encode": "encode", "dispatch": "dispatch",
                      "device_wait": "score_assign",
                      "bind": "bind_net"}
        samples, counts, totals = self._snapshot()
        out: dict[str, dict[str, float]] = {}
        for stage, name in phases.items():
            c = counts.get(name, 0)
            if not c:
                continue
            tot = totals.get(name, 0.0)
            buf = samples.get(name, [])
            out[stage] = {
                "count": float(c),
                "mean_ms": round(tot / c * 1e3, 3),
                "p50_ms": round(_weighted_percentile(buf, 50) * 1e3, 3),
                "p99_ms": round(_weighted_percentile(buf, 99) * 1e3, 3),
                "total_s": round(tot, 3),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._counts.clear()
            self._totals.clear()
