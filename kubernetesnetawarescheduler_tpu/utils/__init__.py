"""Utilities: tracing, checkpointing, logging."""
