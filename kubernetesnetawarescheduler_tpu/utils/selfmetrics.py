"""Self-metrics: Prometheus text exposition of the scheduler itself.

The reference *consumes* Prometheus (node_exporter scrapes,
scheduler.go:275-279) but exposes nothing about itself — its only
introspection was ``println`` of scraped values (scheduler.go:517,
:525-526).  SURVEY.md §5's observability row requires self-metrics:
pods/sec, Score() latency percentiles, queue depth, metric staleness.
This module renders them in the same exposition format the ingest
parser consumes, so an operator points Prometheus at the scheduler the
same way the scheduler points at node_exporters (and our own parser
round-trips it — see tests/test_selfmetrics.py).
"""

from __future__ import annotations

import numpy as np

from kubernetesnetawarescheduler_tpu.utils.timeseries import (
    prom_histogram_lines,
)


_QUANTILES = (0.5, 0.9, 0.99)


class FamilyRegistry:
    """Duplicate-family guard for one exposition render: Prometheus
    silently keeps the FIRST HELP/TYPE it sees and some scrapers drop
    the whole body, so a name collision (two subsystems exporting the
    same family, or a summary vs histogram TYPE clash) must fail
    loudly at render time, not page someone with half-missing
    series."""

    def __init__(self) -> None:
        self._names: set[str] = set()

    def register(self, name: str) -> None:
        if name in self._names:
            raise ValueError(
                f"duplicate metric family {name!r} in /metrics render")
        self._names.add(name)


def _fmt(value: float) -> str:
    if value != value:  # NaN (empty percentile source)
        return "NaN"
    return repr(float(value))


def render_metrics(loop) -> str:
    """One exposition-format body for a
    :class:`~kubernetesnetawarescheduler_tpu.core.loop.SchedulerLoop`."""
    enc = loop.encoder
    lines: list[str] = []
    _register = FamilyRegistry().register

    def counter(name: str, value: float, help_: str) -> None:
        _register(name)
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(value)}")

    def gauge(name: str, value: float, help_: str) -> None:
        _register(name)
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {_fmt(value)}")

    def hist(name: str, help_: str, snaps) -> None:
        """Native-histogram family from (labels, snapshot) pairs —
        HELP/TYPE once, then every label set's buckets."""
        _register(name)
        first = True
        for labels, snap in snaps:
            lines.extend(prom_histogram_lines(
                name, help_, snap, labels=labels, header=first))
            first = False

    counter("netaware_pods_scheduled_total", loop.scheduled,
            "Pods successfully bound")
    counter("netaware_pods_unschedulable_total", loop.unschedulable,
            "Pods with no feasible node")
    counter("netaware_bind_failures_total", loop.bind_failures,
            "Bind attempts rejected or errored")
    counter("netaware_preemptions_total", loop.preemptions,
            "Pods evicted to make room for higher-priority pods")
    counter("netaware_burst_cycles_total",
            getattr(loop, "burst_cycles", 0),
            "Backlog bursts served (multi-batch single-dispatch "
            "cycles)")
    gauge("netaware_queue_depth", len(loop.queue),
          "Pending pods waiting in the scheduling queue")
    counter("netaware_queue_dropped_total",
            getattr(loop.queue, "dropped", 0),
            "Pods dropped on queue overflow (recovered by resync)")

    with enc._lock:
        valid = enc._node_valid.copy()
        ages = enc._metrics_age[valid]
        overflow = (enc.labels.overflow_drops + enc.taints.overflow_drops
                    + enc.groups.overflow_drops)
        ledger_size = len(enc._committed)
        early_releases = len(enc._early_releases)
    gauge("netaware_usage_ledger_entries", float(ledger_size),
          "Bound pods with committed usage (release/reconcile source)")
    gauge("netaware_early_release_markers", float(early_releases),
          "Terminations seen before their commit (in-flight races)")
    gauge("netaware_nodes_ready", float(valid.sum()),
          "Nodes currently schedulable")
    gauge("netaware_nodes_registered", float(enc.num_nodes),
          "Nodes known to the encoder")
    counter("netaware_intern_overflow_total", float(overflow),
            "Constraint keys dropped by lenient interning")
    counter("netaware_constraint_degraded_pods_total",
            float(getattr(enc, "degraded_total", 0)),
            "Pods that lost constraint keys to interner overflow "
            "(each also gets a ConstraintDegraded event)")
    counter("netaware_encode_shape_cache_hits_total",
            float(getattr(enc, "shape_cache_hits", 0)),
            "Pods encoded from the constraint-shape cache")
    counter("netaware_encode_shape_cache_misses_total",
            float(getattr(enc, "shape_cache_misses", 0)),
            "Constraint-shape computes (cache misses; the cache is "
            "bounded, so evictions recount shapes — a high and "
            "growing miss RATE means mostly-unique constraint sets)")

    # Control-plane brownout resilience (k8s/kubeclient.py,
    # ISSUE 4): breaker state, retry spend, watch-gap/relist audit
    # activity and the degraded-mode parking counters.
    breaker = getattr(loop, "breaker", None)
    if breaker is not None:
        gauge("netaware_apiserver_breaker_state",
              float(breaker.state_code),
              "Circuit breaker over API-server health "
              "(0=closed, 1=half_open, 2=open/degraded)")
        counter("netaware_apiserver_breaker_opens_total",
                float(breaker.opens_total),
                "Times the breaker tripped open (brownout onsets)")
        counter("netaware_apiserver_failures_total",
                float(breaker.failures_total),
                "Brownout-class API failures observed (5xx/429/"
                "connection errors)")
    budget = getattr(getattr(loop, "client", None), "retry_budget",
                     None)
    if budget is not None:
        counter("netaware_api_retries_total",
                float(budget.retries_total),
                "API request retries taken from the per-cycle budget")
        counter("netaware_api_retry_budget_exhausted_total",
                float(budget.exhausted_total),
                "Retries denied because the cycle's budget was spent")
    counter("netaware_watch_gaps_total",
            float(getattr(loop, "watch_gaps", 0)),
            "Watch-stream gaps detected (drops, 410 Gone)")
    counter("netaware_relists_total",
            float(getattr(loop, "relists", 0)),
            "Full relist reconciliation audits run after watch gaps")
    counter("netaware_relist_repairs_total",
            float(getattr(loop, "relist_repairs", 0)),
            "Drift items repaired by relist audits (missed nodes, "
            "re-enqueued pods, released ledger entries)")
    counter("netaware_parked_dropped_total",
            float(getattr(loop, "parked_dropped", 0)),
            "Parked pods evicted from the unschedulable backlog at "
            "capacity (each also gets a FailedScheduling event)")
    counter("netaware_binds_parked_total",
            float(getattr(loop, "binds_parked_total", 0)),
            "Pod binds parked by an open breaker (degraded mode)")
    counter("netaware_binds_adopted_total",
            float(getattr(loop, "binds_adopted", 0)),
            "Bound-elsewhere conflicts adopted into the ledger "
            "(our earlier bind applied but unacknowledged)")
    counter("netaware_binds_redirected_total",
            float(getattr(loop, "binds_redirected", 0)),
            "Binds re-routed to the ledger's recorded node (pod was "
            "already committed, e.g. restored from a checkpoint)")
    gauge("netaware_parked_binds_backlog",
          float(len(getattr(loop, "_parked_binds", ()))),
          "Bind batches currently parked awaiting breaker recovery")
    # Coalesced async binds + multi-cycle serving (r16): the bounded-
    # inflight proof and the coalescing win, scrapeable live (the
    # bench artifact's bind_split block is the offline counterpart).
    gauge("netaware_bind_inflight",
          float(getattr(loop, "bind_inflight", 0)),
          "Async bind batches inside their API fanout right now "
          "(bounded by cfg.bind_max_inflight)")
    counter("netaware_bind_coalesced_total",
            float(getattr(loop, "bind_coalesced_total", 0)),
            "Queued bind batches folded into an adjacent batch's "
            "fanout (sorted by node/namespace before POSTing)")

    # Learned topology model (netmodel/): direct-probe pair coverage,
    # prediction-residual quantiles, planner selection entropy and the
    # residual monitor's degradation count.
    netmodel = getattr(enc, "netmodel", None)
    if netmodel is not None:
        gauge("netaware_netmodel_pair_coverage_fraction",
              netmodel.coverage_fraction(enc.num_nodes),
              "Fraction of node pairs ever directly probed (the rest "
              "ride model estimates)")
        p50, p99 = netmodel.residual_quantiles()
        gauge("netaware_netmodel_residual_p50", p50,
              "Median |log-bandwidth residual| of fresh probes vs "
              "model prediction")
        gauge("netaware_netmodel_residual_p99", p99,
              "p99 |log-bandwidth residual| of fresh probes vs model "
              "prediction")
        counter("netaware_netmodel_sgd_steps_total",
                float(netmodel.steps_total),
                "Jitted mini-batch SGD steps dispatched")
        counter("netaware_netmodel_link_degradations_total",
                float(netmodel.degradations_total),
                "Fresh measurements diverging sharply from a confident "
                "prediction (each also gets a LinkDegraded event)")
    planner = getattr(loop, "probe_planner", None)
    if planner is not None:
        gauge("netaware_netmodel_probe_selection_entropy_bits",
              float(planner.last_entropy_bits),
              "Shannon entropy of the last probe cycle's EIG score "
              "distribution (collapse = planner fixation)")
    orch = getattr(loop, "probe_orchestrator", None)
    if orch is not None:
        stats = orch.staleness()
        gauge("netaware_probe_pair_coverage_fraction",
              float(stats["coverage_fraction"]),
              "Fraction of node pairs with a tracked recent probe")
        gauge("netaware_probe_mean_age_seconds",
              float(stats["mean_age_s"]),
              "Mean age of tracked pair probes")
        gauge("netaware_probe_max_age_seconds",
              float(stats["max_age_s"]),
              "Max age of tracked pair probes")
        counter("netaware_probe_pairs_pruned_total",
                float(getattr(orch, "pruned_total", 0)),
                "Per-pair probe bookkeeping entries pruned past the "
                "forget horizon")
        # Ingest quarantine: samples refused at the staging boundary,
        # per reason — growth here means a sick probe agent is
        # emitting garbage, not that links are bad.
        quarantined = getattr(orch, "quarantined", None)
        if quarantined:
            _register("netaware_ingest_quarantined_total")
            lines.append("# HELP netaware_ingest_quarantined_total "
                         "Probe samples refused at the staging "
                         "boundary (range validation)")
            lines.append("# TYPE netaware_ingest_quarantined_total "
                         "counter")
            for reason, n in sorted(quarantined.items()):
                lines.append(
                    "netaware_ingest_quarantined_total"
                    f'{{reason="{reason}"}} {_fmt(float(n))}')

    # Decision-level tracing (utils/flight.py): the cycle sequence and
    # drop counter make recorder overflow VISIBLE — if dropped grows
    # between scrapes, /debug/trace no longer covers the full window
    # and flight_recorder_size needs raising before the next incident.
    flight = getattr(loop, "flight", None)
    if flight is not None:
        gauge("netaware_cycle_seq", float(flight.cycle_seq),
              "Monotonic serving-cycle sequence number (flight "
              "recorder span ids)")
        counter("netaware_flight_dropped_total", float(flight.dropped),
                "Cycle spans evicted from the flight recorder's ring "
                "buffer (overflow)")
        gauge("netaware_flight_spans", float(len(flight)),
              "Cycle spans currently retained by the flight recorder")
        gauge("netaware_explain_records", float(flight.explains_len()),
              "Placement explain records currently retained "
              "(enable_explain)")

    # State integrity & self-healing (core/integrity.py): audit cadence
    # and the repair ladder's per-rung spend.  unrepaired_total > 0 is
    # a page — the ladder exhausted itself and placements may be
    # computed from corrupt state (see docs/OPERATIONS.md "State drift
    # & corruption").
    auditor = getattr(loop, "integrity", None)
    if auditor is not None:
        counter("netaware_integrity_audits_total",
                float(auditor.audits_total),
                "Anti-entropy audit passes (digest compare of device "
                "planes vs shadow re-encode)")
        counter("netaware_integrity_drift_total",
                float(auditor.drift_detected_total),
                "Audits that detected device/staging digest drift")
        counter("netaware_integrity_drift_rows_total",
                float(auditor.drift_rows_total),
                "Total drifted rows localized across all audits")
        counter("netaware_integrity_unrepaired_total",
                float(auditor.unrepaired_total),
                "Audits whose drift survived the FULL repair ladder")
        counter("netaware_integrity_watchdog_dumps_total",
                float(auditor.watchdog_dumps),
                "Flight-recorder crash dumps fired by the stuck-audit "
                "watchdog")
        gauge("netaware_integrity_last_audit_ms",
              float(auditor.last_audit_ms),
              "Wall time of the most recent audit pass")
        _register("netaware_integrity_repairs_total")
        lines.append("# HELP netaware_integrity_repairs_total Repairs "
                     "applied, by escalation-ladder rung")
        lines.append("# TYPE netaware_integrity_repairs_total counter")
        for rung, n in sorted(auditor.repairs.items()):
            lines.append("netaware_integrity_repairs_total"
                         f'{{rung="{rung}"}} {_fmt(float(n))}')
    chaos = getattr(loop, "state_chaos", None)
    if chaos is not None:
        _register("netaware_state_faults_injected_total")
        lines.append("# HELP netaware_state_faults_injected_total "
                     "State-layer faults injected by the chaos "
                     "injector, by class")
        lines.append("# TYPE netaware_state_faults_injected_total "
                     "counter")
        for kind, n in sorted(chaos.injected.items()):
            lines.append("netaware_state_faults_injected_total"
                         f'{{fault="{kind}"}} {_fmt(float(n))}')

    # Extender webhook micro-batcher (api/extender._ScoreBatcher):
    # dispatch count exposes the coalescing rate (requests served /
    # dispatches = mean batch).
    batcher = getattr(loop, "_extender_batcher", None)
    if batcher is not None:
        counter("netaware_extender_dispatches_total",
                float(batcher.dispatches),
                "Score-kernel dispatches serving webhook requests")
        counter("netaware_extender_requests_total",
                float(batcher.requests),
                "Webhook score requests (filter+prioritize)")

    # Incremental device-resident state (core/loop._static_for +
    # core/encode delta ingest): refresh activity, sync-fallback count
    # (a growing share means the staleness contract keeps breaching —
    # tune static_max_staleness_s / static_max_versions_behind, see
    # OPERATIONS.md), delta-vs-full snapshot upload traffic, and the
    # staleness of the static each Score() actually served.
    counter("netaware_static_refresh_total",
            float(getattr(loop, "static_refresh_total", 0)),
            "Assign-static rebuilds (delta or full; async or sync)")
    counter("netaware_static_sync_builds_total",
            float(getattr(loop, "static_sync_builds", 0)),
            "Static rebuilds forced synchronous by the staleness "
            "contract (async mode's bounded fallback)")
    counter("netaware_snapshot_delta_bytes_total",
            float(getattr(enc, "snapshot_delta_bytes_total", 0)),
            "Host-to-device snapshot bytes moved as dirty-index "
            "scatter updates")
    counter("netaware_snapshot_full_bytes_total",
            float(getattr(enc, "snapshot_full_bytes_total", 0)),
            "Host-to-device snapshot bytes moved as full-array "
            "re-uploads")

    # Fused-step accounting (r9, core/loop._note_dispatch): recompile
    # and donation observables.  jit_cache_miss_total must FLATLINE
    # after warmup — steady-state growth is a recompile the bucketed
    # batch-size ladder should have prevented (regression-tested in
    # tests/test_winner_fusion.py).  The serving loop's dispatches
    # never donate (its snapshot is encoder-owned, patched in place by
    # delta ingest), so donation_skipped grows one per dispatch while
    # donated moves only on owned-state paths (bench chain, replay
    # folds) — a nonzero donated here would mean the loop donated
    # buffers it does not own.
    counter("netaware_jit_cache_miss_total",
            float(getattr(loop, "jit_cache_miss_total", 0)),
            "Executable-cache growth across the tracked jitted "
            "entry points (recompiles; zero after warmup)")
    counter("netaware_donated_dispatches_total",
            float(getattr(loop, "donated_total", 0)),
            "Device dispatches that donated the cluster-state "
            "buffers (fused_schedule_step on owned state)")
    counter("netaware_donation_skipped_total",
            float(getattr(loop, "donation_skipped_total", 0)),
            "Device dispatches that could NOT donate (the serving "
            "snapshot is encoder-owned and patched in place)")
    # The serving thread and the async refresh worker append to these
    # deques lock-free (appends are atomic; only iteration can see a
    # mutation and raise RuntimeError) — retry the snapshot instead of
    # intermittently 500ing the scrape.
    def _snap_deque(name: str) -> np.ndarray:
        dq = getattr(loop, name, ())
        for _ in range(3):
            try:
                return np.asarray(tuple(dq), dtype=float)
            except RuntimeError:
                continue
        return np.zeros((0,))

    refresh_ms = _snap_deque("_static_refresh_ms")
    stale_s = _snap_deque("_staleness_samples")
    if refresh_ms.size:
        _register("netaware_static_refresh_ms")
        lines.append("# HELP netaware_static_refresh_ms Wall time per "
                     "assign-static rebuild (delta or full)")
        lines.append("# TYPE netaware_static_refresh_ms summary")
        for q in _QUANTILES:
            lines.append(
                f'netaware_static_refresh_ms{{quantile="{q:g}"}} '
                f"{_fmt(float(np.quantile(refresh_ms, q)))}")
        lines.append(f"netaware_static_refresh_ms_sum "
                     f"{_fmt(float(refresh_ms.sum()))}")
        lines.append(
            f"netaware_static_refresh_ms_count {refresh_ms.size}")
    if stale_s.size:
        _register("netaware_static_staleness_s")
        lines.append("# HELP netaware_static_staleness_s Age of the "
                     "static each Score() call served (async refresh; "
                     "0 = current)")
        lines.append("# TYPE netaware_static_staleness_s summary")
        for q in _QUANTILES:
            lines.append(
                f'netaware_static_staleness_s{{quantile="{q:g}"}} '
                f"{_fmt(float(np.quantile(stale_s, q)))}")
        lines.append(f"netaware_static_staleness_s_sum "
                     f"{_fmt(float(stale_s.sum()))}")
        lines.append(
            f"netaware_static_staleness_s_count {stale_s.size}")

    # Conflict-round distribution over recent serving cycles (one
    # sample per batch, parallel assigner): whether score latency is
    # matmul-bound or round-bound — the bench's rounds_p50/p99, live.
    round_lock = getattr(loop, "_round_lock", None)
    if round_lock is not None:
        with round_lock:
            # Snapshot under the lock: the serving thread appends
            # while this scrape iterates, and a deque mutated during
            # iteration raises (intermittent 500s on /metrics).
            rounds = np.asarray(tuple(loop.round_samples), dtype=float)
    else:
        rounds = np.zeros((0,))
    if rounds.size:
        _register("netaware_conflict_rounds")
        lines.append("# HELP netaware_conflict_rounds Conflict-"
                     "resolution rounds per scheduled batch")
        lines.append("# TYPE netaware_conflict_rounds summary")
        for q in _QUANTILES:
            lines.append(
                f'netaware_conflict_rounds{{quantile="{q:g}"}} '
                f"{_fmt(float(np.quantile(rounds, q)))}")
        lines.append(
            f"netaware_conflict_rounds_sum {_fmt(float(rounds.sum()))}")
        lines.append(f"netaware_conflict_rounds_count {rounds.size}")

    # Metric staleness distribution over ready nodes — the quantity the
    # exp(-age/tau) decay consumes.
    _register("netaware_metric_staleness_seconds")
    lines.append("# HELP netaware_metric_staleness_seconds Age of each "
                 "ready node's last telemetry sample")
    lines.append("# TYPE netaware_metric_staleness_seconds summary")
    for q in _QUANTILES:
        v = float(np.quantile(ages, q)) if ages.size else float("nan")
        lines.append(
            f'netaware_metric_staleness_seconds{{quantile="{q:g}"}} '
            f"{_fmt(v)}")
    lines.append(f"netaware_metric_staleness_seconds_count {ages.size}")
    lines.append("netaware_metric_staleness_seconds_sum "
                 f"{_fmt(float(ages.sum()) if ages.size else 0.0)}")

    # Per-phase latency summaries (encode / score_assign / bind) — p99
    # Score() latency is a north-star metric (BASELINE.json).
    _register("netaware_phase_latency_seconds")
    lines.append("# HELP netaware_phase_latency_seconds Wall time per "
                 "scheduling phase")
    lines.append("# TYPE netaware_phase_latency_seconds summary")
    for phase, stats in sorted(loop.timer.summary().items()):
        for q in _QUANTILES:
            v = loop.timer.percentile(phase, q * 100)
            lines.append(
                f'netaware_phase_latency_seconds{{phase="{phase}",'
                f'quantile="{q:g}"}} {_fmt(v)}')
        lines.append(
            f'netaware_phase_latency_seconds_count{{phase="{phase}"}} '
            f"{stats['count']:g}")
        lines.append(
            f'netaware_phase_latency_seconds_sum{{phase="{phase}"}} '
            f"{_fmt(stats['total_s'])}")

    # Native-histogram ride-alongs (r11, utils/timeseries.py): the
    # summary families above keep their series names for existing
    # dashboards; these ``_hist`` families export the SAME
    # observations as cumulative le-buckets with exact never-evicting
    # counts, so "how many cycles ever crossed 5 ms" survives the
    # percentile window sliding and sums across replicas.
    hists = getattr(loop.timer, "hists", None)
    if hists:
        hist("netaware_phase_latency_seconds_hist",
             "Wall time per scheduling phase (log-bucketed native "
             "histogram; exact counts)",
             [(f'phase="{phase}"', h.snapshot())
              for phase, h in sorted(hists.items())])
    for attr, fam, help_ in (
            ("_static_refresh_ms", "netaware_static_refresh_ms_hist",
             "Wall time per assign-static rebuild, milliseconds "
             "(log-bucketed native histogram)"),
            ("_staleness_samples", "netaware_static_staleness_s_hist",
             "Age of the static each Score() call served, seconds "
             "(log-bucketed native histogram)"),
            ("round_samples", "netaware_conflict_rounds_hist",
             "Conflict-resolution rounds per scheduled batch "
             "(log-bucketed native histogram)"),
            ("_retire_lag", "netaware_multicycle_retire_lag",
             "Logical cycles between a multicycle wave's dispatch "
             "and its retire (log-bucketed native histogram)")):
        h = getattr(loop, attr, None)
        snap_fn = getattr(h, "snapshot", None)
        if snap_fn is not None:
            snap = snap_fn()
            if snap["count"]:
                hist(fam, help_, [("", snap)])

    # Pipeline stage budgets (pipelined serving datapath): the live
    # counterpart of the bench artifact's pipeline_budgets block —
    # encode / dispatch / device_wait / bind, so overlap health is
    # scrapeable, not just benchable.  Empty until a pipelined burst
    # has run.
    budgets = loop.timer.pipeline_budgets()
    if budgets:
        _register("netaware_pipeline_stage_ms")
        lines.append("# HELP netaware_pipeline_stage_ms Per-stage "
                     "serving-pipeline budget in milliseconds")
        lines.append("# TYPE netaware_pipeline_stage_ms gauge")
        for stage, b in sorted(budgets.items()):
            for stat in ("mean_ms", "p50_ms", "p99_ms"):
                lines.append(
                    f'netaware_pipeline_stage_ms{{stage="{stage}",'
                    f'stat="{stat[:-3]}"}} {_fmt(b[stat])}')

    # Outcome observability (r11, obs/quality.py): did the placements
    # the scheduler committed turn out to be GOOD?  Regret is in the
    # same desirability units the score kernel optimized; calibration
    # residuals measure how honest the score-time network prediction
    # was against later probe truth.
    quality = getattr(loop, "quality", None)
    if quality is not None:
        qs = quality.summary()
        counter("netaware_quality_commits_noted_total",
                float(qs["noted_total"]),
                "Bound pods whose score-time prediction was captured "
                "for outcome joining")
        counter("netaware_quality_outcomes_total",
                float(qs["harvested_total"]),
                "Placement outcomes evaluated against observed probe "
                "state (regret + calibration)")
        counter("netaware_quality_no_peer_total",
                float(qs["no_peer_total"]),
                "Bound pods skipped by the quality observer (no "
                "resolvable peers at commit time)")
        counter("netaware_quality_calibration_samples_total",
                float(qs["calibration_samples"]),
                "Pod-peer samples contributing to netmodel "
                "calibration residuals")
        counter("netaware_quality_pending_dropped_total",
                float(qs["pending_dropped"]),
                "Pending observations evicted before harvest "
                "(capacity)")
        gauge("netaware_quality_ring_depth", float(qs["ring_depth"]),
              "Evaluated outcomes retained in the bounded ring")
        gauge("netaware_quality_pending_depth", float(qs["pending"]),
              "Commits awaiting their next harvest join")
        hist("netaware_quality_regret",
             "Per-pod placement regret vs the best feasible "
             "alternative, in net-desirability score units",
             [("", quality.regret_hist.snapshot())])
        hist("netaware_quality_bw_residual_log1p",
             "Per-pod |log1p(predicted bw) - log1p(observed bw)| "
             "calibration residual",
             [("", quality.bw_residual_hist.snapshot())])

    # SLO burn-rate engine (r11, obs/slo.py): multi-window burn per
    # objective, plus a 0/1 burning flag alertmanager can gate on
    # without re-deriving the window math.
    slo = getattr(loop, "slo", None)
    if slo is not None:
        ss = slo.snapshot()
        counter("netaware_slo_evaluations_total",
                float(ss["evaluations_total"]),
                "SLO engine evaluation passes")
        counter("netaware_slo_burn_events_total",
                float(ss["burn_events_total"]),
                "Not-burning -> burning transitions (each also gets "
                "an SLOBurn event)")
        _register("netaware_slo_burn_rate")
        lines.append("# HELP netaware_slo_burn_rate Error-budget burn "
                     "rate per objective and window (1.0 = burning "
                     "exactly at budget)")
        lines.append("# TYPE netaware_slo_burn_rate gauge")
        for name, obj in sorted(ss["objectives"].items()):
            for window in ("fast", "slow"):
                lines.append(
                    f'netaware_slo_burn_rate{{objective="{name}",'
                    f'window="{window}"}} '
                    f"{_fmt(obj[f'burn_{window}'])}")
        _register("netaware_slo_burning")
        lines.append("# HELP netaware_slo_burning Whether the "
                     "objective is burning on BOTH windows (1 = page)")
        lines.append("# TYPE netaware_slo_burning gauge")
        for name, obj in sorted(ss["objectives"].items()):
            lines.append(
                f'netaware_slo_burning{{objective="{name}"}} '
                f"{1 if obj['burning'] else 0}")

    # Continuous rebalancing (r12, core/rebalance.py): how often the
    # descheduler acted, what held it back (the skip breakdown is the
    # rebalance-storm runbook's first read), and the crash-safety
    # canary — half_moved_gangs must stay 0 forever.
    rb = getattr(loop, "rebalance", None)
    if rb is not None:
        rs = rb.summary()
        counter("netaware_rebalance_scans_total",
                float(rs["scans_total"]),
                "Descheduler improvement scans over the bound-pod "
                "ledger")
        counter("netaware_rebalance_moves_total",
                float(rs["moves_total"]),
                "Live migrations staged in the migration ledger")
        counter("netaware_rebalance_moves_completed_total",
                float(rs["moves_completed"]),
                "Migrations whose every member re-bound (ledger "
                "entry cleared)")
        counter("netaware_rebalance_moves_reverted_total",
                float(rs["moves_reverted"]),
                "Migrations reverted at their deadline (unbound "
                "members rolled back)")
        counter("netaware_rebalance_evictions_total",
                float(rs["pods_evicted_total"]),
                "Pods evicted by the rebalancer (the disruption the "
                "eviction budget bounds)")
        counter("netaware_rebalance_half_moved_gangs_total",
                float(rs["half_moved_gangs"]),
                "Gangs observed part-bound at a revert deadline — "
                "MUST stay 0 (the migration ledger's atomicity "
                "canary)")
        counter("netaware_rebalance_pins_skipped_total",
                float(rs["pins_skipped"]),
                "Single-pod moves whose target pin could not land "
                "(uid still committed when the pin was attempted) — "
                "the move degrades to a bare eviction")
        for key, help_txt in (
                ("skipped_gain", "below the relative-gain bar"),
                ("skipped_age", "younger than the placement-age "
                                "floor"),
                ("skipped_cooldown", "inside the per-pod move "
                                     "cooldown"),
                ("skipped_budget", "over the eviction budget"),
                ("skipped_disruption", "blocked by a PDB-style "
                                       "group floor")):
            counter(f"netaware_rebalance_{key}_total",
                    float(rs[key]),
                    f"Rebalance candidates skipped: {help_txt}")
        _register("netaware_rebalance_triggers_total")
        lines.append("# HELP netaware_rebalance_triggers_total "
                     "Executed moves by trigger source")
        lines.append("# TYPE netaware_rebalance_triggers_total "
                     "counter")
        for trig in ("link", "regret", "drain"):
            lines.append(
                f'netaware_rebalance_triggers_total{{trigger='
                f'"{trig}"}} {_fmt(float(rs["triggers_" + trig]))}')
        gauge("netaware_rebalance_moves_inflight",
              float(rs["moves_inflight"]),
              "Migrations currently staged in the ledger (crash-safe "
              "window)")
        gauge("netaware_rebalance_last_scan_candidates",
              float(rs["last_scan_candidates"]),
              "Improvement candidates surviving hysteresis at the "
              "last scan")
        # Elastic gang reshaping (r17): one labeled counter family by
        # outcome — a NEW family, no existing name renamed.  Emitted
        # only when the rebalancer carries the reshape block (pre-r17
        # scrape configs see an unchanged exposition otherwise).
        resh = rs.get("reshape")
        if isinstance(resh, dict) and resh.get("enabled"):
            _register("netaware_gang_reshape_total")
            lines.append("# HELP netaware_gang_reshape_total "
                         "Elastic gang reshapes by outcome "
                         "(committed = new realization bound; "
                         "reverted = settled back / degraded; "
                         "half_shaped MUST stay 0)")
            lines.append("# TYPE netaware_gang_reshape_total counter")
            for outcome, val in (
                    ("committed", resh["reshapes_completed"]),
                    ("reverted", resh["reshapes_reverted"]),
                    ("half_shaped", resh["half_shaped_gangs"])):
                lines.append(
                    f'netaware_gang_reshape_total{{outcome='
                    f'"{outcome}"}} {_fmt(float(val))}')
            gauge("netaware_gang_reshapes_inflight",
                  float(resh["reshapes_inflight"]),
                  "Reshapes currently staged in the reshape ledger "
                  "(crash-safe window)")

    # Learned scoring policy (r15, policy/): training volume, shadow
    # disagreement (the promotion runbook's first read — a promotion
    # with near-zero disagreement changes nothing; one with high
    # disagreement is high-variance), and the gate's verdict history.
    policy = getattr(loop, "policy", None)
    if policy is not None:
        ps = policy.summary()
        counter("netaware_policy_train_steps_total",
                float(ps["steps_total"]),
                "Adam mini-batch steps dispatched over the example "
                "ring")
        counter("netaware_policy_examples_total",
                float(ps["examples_total"]),
                "Training examples harvested from the explain/"
                "outcome join")
        counter("netaware_policy_promotions_total",
                float(ps["promotions_total"]),
                "Candidate weight vectors promoted through the "
                "counterfactual replay gate")
        counter("netaware_policy_rejections_total",
                float(ps["rejections_total"]),
                "Gate runs that refused promotion (no trace, records "
                "regression, or below the replay margin)")
        counter("netaware_policy_shadow_disagreement_total",
                float(ps["shadow_disagreement_total"]),
                "Recorded decisions the shadow policy would have "
                "placed on a different node")
        counter("netaware_policy_shadow_agree_total",
                float(ps["shadow_agree_total"]),
                "Recorded decisions the shadow policy agrees with")
        gauge("netaware_policy_ring_depth",
              float(ps["ring_depth"]),
              "Training examples resident in the bounded ring")
        gauge("netaware_policy_version", float(ps["version"]),
              "Policy parameter version (increments per train tick)")
        gauge("netaware_policy_promoted_version",
              float(ps["promoted_version"]),
              "Parameter version live in the scorer (0 = hand-tuned "
              "weights, never promoted)")

    return "\n".join(lines) + "\n"


def render_fleet_metrics(fleet) -> str:
    """Exposition-format body for a
    :class:`~kubernetesnetawarescheduler_tpu.fleet.server.FleetServer`
    — the consolidation-level view the per-tenant ``render_metrics``
    bodies cannot see: how many tenants share each padding bucket,
    batched-dispatch volume (lanes per dispatch is the consolidation
    ratio, live), per-tenant queue depth under a shared device
    program (the noisy-neighbor first read), and transfer-registry
    size."""
    s = fleet.summary()
    lines: list[str] = []
    _register = FamilyRegistry().register

    def counter(name: str, value: float, help_: str) -> None:
        _register(name)
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_fmt(value)}")

    counter("netaware_fleet_cycles_total", float(s["cycles_total"]),
            "Batched serving cycles across all buckets")
    counter("netaware_fleet_dispatches_total",
            float(s["dispatches_total"]),
            "Vmapped device dispatches (one per bucket cycle with "
            "work)")
    counter("netaware_fleet_dispatch_lanes_total",
            float(s["dispatch_lanes_total"]),
            "Tenant lanes carried by those dispatches (lanes/"
            "dispatch = live consolidation ratio)")
    counter("netaware_fleet_transfers_total",
            float(s["transfer"]["transfers_total"]),
            "Policies warm-started from the transfer registry")

    _register("netaware_fleet_tenants")
    lines.append("# HELP netaware_fleet_tenants Tenants packed into "
                 "each node-count padding bucket")
    lines.append("# TYPE netaware_fleet_tenants gauge")
    for nodes, blk in sorted(s["buckets"].items()):
        lines.append(f'netaware_fleet_tenants{{bucket_nodes='
                     f'"{nodes}"}} {_fmt(float(len(blk["tenants"])))}')

    _register("netaware_fleet_bucket_capacity")
    lines.append("# HELP netaware_fleet_bucket_capacity Padded lane "
                 "count of each bucket's batched dispatch")
    lines.append("# TYPE netaware_fleet_bucket_capacity gauge")
    for nodes, blk in sorted(s["buckets"].items()):
        lines.append(f'netaware_fleet_bucket_capacity{{bucket_nodes='
                     f'"{nodes}"}} {_fmt(float(blk["capacity"]))}')

    _register("netaware_fleet_tenant_queue_depth")
    lines.append("# HELP netaware_fleet_tenant_queue_depth Pending "
                 "pods per tenant (a deep queue behind a shared "
                 "dispatch is the noisy-neighbor signature)")
    lines.append("# TYPE netaware_fleet_tenant_queue_depth gauge")
    for name, blk in sorted(s["tenants"].items()):
        lines.append(f'netaware_fleet_tenant_queue_depth{{tenant='
                     f'"{name}"}} {_fmt(float(blk["queue_depth"]))}')

    _register("netaware_fleet_tenant_scheduled_total")
    lines.append("# HELP netaware_fleet_tenant_scheduled_total Pods "
                 "scheduled per tenant since onboarding")
    lines.append("# TYPE netaware_fleet_tenant_scheduled_total "
                 "counter")
    for name, blk in sorted(s["tenants"].items()):
        lines.append(
            f'netaware_fleet_tenant_scheduled_total{{tenant='
            f'"{name}"}} {_fmt(float(blk["scheduled"]))}')

    _register("netaware_fleet_registry_donors")
    lines.append("# HELP netaware_fleet_registry_donors Promoted "
                 "donor policies resident in the transfer registry")
    lines.append("# TYPE netaware_fleet_registry_donors gauge")
    lines.append(f"netaware_fleet_registry_donors "
                 f"{_fmt(float(len(s['transfer']['donors'])))}")

    return "\n".join(lines) + "\n"
