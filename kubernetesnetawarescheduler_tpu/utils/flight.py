"""Decision-level tracing: cycle spans, flight recorder, explainability.

Every aggregate the repo exposes today (PhaseTimer percentiles,
/metrics summaries, bench p99 blocks) answers "how are cycles doing
on average" — none answers "which cycle regressed" or "why did THIS
pod land on THAT node".  Kubernetes' own scheduler ships per-plugin
scoring traces and ``--v=10`` placement explanations for exactly this
gap.  This module is the repro's equivalent:

* :class:`CycleSpan` — one structured record per serving cycle (wall +
  monotonic timestamps, pod uids, per-phase child spans reusing the
  PhaseTimer phase names, queue depth, static-refresh staleness /
  version lag, breaker + degraded-mode state, delta-vs-full ingest
  bytes, fault class).
* :class:`FlightRecorder` — a bounded ring buffer of the most recent
  spans plus a bounded store of per-pod explain records.  Overflow
  evicts oldest and counts ``dropped`` (scrapeable as
  ``netaware_flight_dropped_total``); RSS stays O(capacity) no matter
  how long the serve runs.
* :func:`FlightRecorder.to_chrome_trace` — Chrome trace-event JSON
  (Perfetto-loadable: ``ph:"X"`` complete events, phases nested under
  their cycle by time containment on one tid).
* :func:`FlightRecorder.crash_dump` — post-mortem file written on
  SIGTERM/fault from serve.py's shutdown path.

The recorder is observation-only: span capture happens host-side
around the existing timed blocks and never feeds back into scoring, so
placements are bit-identical with the recorder on or off (pinned by
tests/test_flight.py).
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator, Mapping

__all__ = [
    "CycleSpan",
    "FlightRecorder",
    "NULL_SPAN",
    "SpanBuilder",
]


@dataclass(frozen=True)
class CycleSpan:
    """One serving cycle, committed when the cycle's effects commit
    (serial: end of ``schedule_pods``; pipelined: at retire — the same
    point usage commits, so a crash never leaves a span for a cycle
    whose binds were lost)."""

    cycle_id: int
    path: str                  # serial | burst | pipelined | gang
    t_wall: float              # epoch seconds at cycle start
    t_mono: float              # perf_counter seconds at cycle start
    dur_s: float
    n_pods: int
    pod_uids: tuple[str, ...]
    queue_depth: int
    # (phase_name, start_rel_s, dur_s) — PhaseTimer names, offsets
    # relative to t_mono so children always nest inside the cycle.
    phases: tuple[tuple[str, float, float], ...]
    static_staleness_s: float = 0.0
    static_versions_behind: int = 0
    breaker_state: str = "closed"
    degraded: bool = False
    fault_class: str | None = None
    delta_bytes: int = 0
    full_bytes: int = 0
    # Fused-step accounting (ISSUE 9): conflict rounds the device
    # executed for this cycle's batches (max across a burst's batches
    # — the round-bound share of the cycle's device time), and the
    # donation disposition of the dispatch (buffers donated vs skips
    # counted because the caller did not own the state; see
    # core/assign.fused_schedule_step's contract).  Default-valued so
    # spans recorded by older code paths (and pre-r9 crash dumps)
    # deserialize unchanged.
    rounds: int = 0
    donated: int = 0
    donation_skipped: int = 0
    # Outcome observability (ISSUE 11): the SLO objective burning when
    # this cycle committed (None = all objectives healthy or engine
    # off) and the quality observer's outcome-ring depth — so a trace
    # export shows WHICH cycles ran under a burning SLO and how much
    # realized-outcome evidence existed at the time.  Default-valued:
    # pre-r11 spans and crash dumps deserialize unchanged.
    slo_burning: str | None = None
    outcome_ring_depth: int = 0
    # Continuous rebalancing (ISSUE 12): live migrations executed /
    # reverted since the previous committed span (the descheduler
    # runs at maintain cadence, so this is a per-span delta, not a
    # cumulative count).  Default-valued: pre-r12 spans and crash
    # dumps deserialize unchanged.
    rebalance_moves: int = 0
    rebalance_reverts: int = 0
    # Scenario replay (ISSUE 14): which trace phase the replay
    # harness was in when this cycle committed (None = not a replay)
    # and how many trace events had been consumed — the join key
    # between a flight export and the scenario trace that drove it.
    # Default-valued: pre-r13 spans and crash dumps deserialize
    # unchanged.
    scenario_phase: str | None = None
    trace_offset: int = 0
    # Learned scoring policy (ISSUE 15): shadow decisions the policy
    # would have placed differently since the previous committed span
    # (per-span delta, rebalance_moves pattern — shadow ranking runs
    # at maintain cadence) and the policy-parameter version live when
    # this cycle committed (0 = hand-tuned weights, never promoted).
    # Default-valued: pre-r15 spans and crash dumps deserialize
    # unchanged.
    policy_shadow_disagreements: int = 0
    policy_version: int = 0
    # r15 fleet: which logical cluster (tenant) this cycle served.
    # None on solo loops — the pre-r15-compatible default, so old
    # traces and crash dumps deserialize unchanged and trace_check
    # validates it only-when-present.
    cluster_id: str | None = None
    # Persistent multi-cycle serving (ISSUE 17): the scan-window size
    # K this logical cycle was dispatched under, and how many cycles
    # after dispatch its retire landed (0 = first wave of its window).
    # None on pre-r16 paths (per-cycle dispatch) — spans are still
    # emitted one-per-logical-cycle from the retire seam, and
    # trace_check validates these only-when-present so old traces
    # lint clean.
    scan_window_k: int | None = None
    retire_lag_cycles: int | None = None
    # Elastic gang reshaping (ISSUE 19): gangs reshaped / reshape
    # reverts since the previous committed span (per-span delta,
    # rebalance_moves pattern — the reshape path runs at maintain
    # cadence).  None on off-path spans (reshaping disabled, or no
    # rebalancer attached) — pre-r17 spans and crash dumps deserialize
    # unchanged and trace_check validates these only-when-present.
    gang_reshapes: int | None = None
    reshape_reverts: int | None = None

    def to_dict(self) -> dict[str, Any]:
        return {
            "cycle_id": self.cycle_id,
            "path": self.path,
            "t_wall": self.t_wall,
            "t_mono": self.t_mono,
            "dur_s": self.dur_s,
            "n_pods": self.n_pods,
            "pod_uids": list(self.pod_uids),
            "queue_depth": self.queue_depth,
            "phases": [list(p) for p in self.phases],
            "static_staleness_s": self.static_staleness_s,
            "static_versions_behind": self.static_versions_behind,
            "breaker_state": self.breaker_state,
            "degraded": self.degraded,
            "fault_class": self.fault_class,
            "delta_bytes": self.delta_bytes,
            "full_bytes": self.full_bytes,
            "rounds": self.rounds,
            "donated": self.donated,
            "donation_skipped": self.donation_skipped,
            "slo_burning": self.slo_burning,
            "outcome_ring_depth": self.outcome_ring_depth,
            "rebalance_moves": self.rebalance_moves,
            "rebalance_reverts": self.rebalance_reverts,
            "scenario_phase": self.scenario_phase,
            "trace_offset": self.trace_offset,
            "policy_shadow_disagreements":
                self.policy_shadow_disagreements,
            "policy_version": self.policy_version,
            "cluster_id": self.cluster_id,
            "scan_window_k": self.scan_window_k,
            "retire_lag_cycles": self.retire_lag_cycles,
            "gang_reshapes": self.gang_reshapes,
            "reshape_reverts": self.reshape_reverts,
        }


class SpanBuilder:
    """Accumulates one cycle's phase child spans, then freezes into a
    :class:`CycleSpan` at commit.  Created at cycle start (dispatch in
    the pipelined path), committed at retire — it may outlive the
    Python frame that started it, which is why it is an object and not
    a context manager."""

    __slots__ = ("cycle_id", "path", "t_wall", "t_mono", "_phases")

    def __init__(self, cycle_id: int, path: str) -> None:
        self.cycle_id = cycle_id
        self.path = path
        self.t_wall = time.time()
        self.t_mono = time.perf_counter()
        self._phases: list[tuple[str, float, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._phases.append(
                (name, start - self.t_mono,
                 time.perf_counter() - start))

    def add_phase(self, name: str, start_mono: float,
                  dur_s: float) -> None:
        """Record a phase from explicit perf_counter timestamps (for
        stages timed outside a ``with`` block, e.g. the pipelined
        device wait measured between dispatch and retire)."""
        self._phases.append((name, start_mono - self.t_mono, dur_s))

    def finish(self, **fields: Any) -> CycleSpan:
        return CycleSpan(
            cycle_id=self.cycle_id,
            path=self.path,
            t_wall=self.t_wall,
            t_mono=self.t_mono,
            dur_s=time.perf_counter() - self.t_mono,
            phases=tuple(self._phases),
            **fields,
        )


class _NullSpan:
    """No-op stand-in when the recorder is disabled
    (``flight_recorder_size=0``): the serving loop keeps one code
    shape and pays only an attribute check."""

    __slots__ = ()
    cycle_id = 0
    path = "off"

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        yield

    def add_phase(self, name: str, start_mono: float,
                  dur_s: float) -> None:
        pass

    def finish(self, **fields: Any) -> None:
        return None


NULL_SPAN = _NullSpan()


class FlightRecorder:
    """Bounded ring buffer of :class:`CycleSpan` + bounded per-pod
    explain store.  All methods are thread-safe (serving thread
    commits, scrape/debug threads read, the async bind worker never
    touches it)."""

    def __init__(self, capacity: int = 512,
                 explain_retain: int = 512) -> None:
        self.capacity = int(capacity)
        self.explain_retain = int(explain_retain)
        self._spans: collections.deque[CycleSpan] = collections.deque(
            maxlen=max(1, self.capacity))
        self._explains: collections.OrderedDict[str, dict[str, Any]] = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self._cycle_seq = 0
        self.dropped = 0
        self.explains_dropped = 0
        # Provenance over restarts: serve.py stamps the checkpoint
        # disposition here so a post-restore trace dump says "recorder
        # is empty because the process restarted (restored)", not
        # "nothing ever ran" (empty-but-versioned contract).
        self.meta: dict[str, Any] = {"checkpoint_state": "fresh"}

    # -- span side ---------------------------------------------------

    def begin(self, path: str) -> SpanBuilder:
        """Issue the next strictly-increasing cycle id and start a
        span.  Cheap: one lock bump + two clock reads."""
        with self._lock:
            self._cycle_seq += 1
            cid = self._cycle_seq
        return SpanBuilder(cid, path)

    @property
    def cycle_seq(self) -> int:
        with self._lock:
            return self._cycle_seq

    def commit(self, span: CycleSpan | None) -> None:
        if span is None:
            return
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(span)

    def spans(self) -> list[CycleSpan]:
        with self._lock:
            return list(self._spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- explain side ------------------------------------------------

    def put_explain(self, record: Mapping[str, Any]) -> None:
        uid = str(record["pod_uid"])
        with self._lock:
            self._explains.pop(uid, None)
            self._explains[uid] = dict(record)
            while len(self._explains) > max(1, self.explain_retain):
                self._explains.popitem(last=False)
                self.explains_dropped += 1

    def get_explain(self, pod_uid: str) -> dict[str, Any] | None:
        with self._lock:
            rec = self._explains.get(pod_uid)
            return dict(rec) if rec is not None else None

    def explains(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._explains.values()]

    def explains_len(self) -> int:
        with self._lock:
            return len(self._explains)

    # -- export ------------------------------------------------------

    def to_chrome_trace(self) -> dict[str, Any]:
        """Chrome trace-event JSON (the ``{"traceEvents": [...]}``
        object form Perfetto loads directly).  One pid/tid; cycles are
        ``ph:"X"`` complete events, phases are ``ph:"X"`` events whose
        [ts, ts+dur] interval is clamped inside their cycle's, so the
        viewer nests them and tools/trace_check.py can verify no span
        is orphaned."""
        # One lock acquisition for spans AND counters: a commit landing
        # between two separate snapshots would make the recorder block
        # disagree with the event list (tools/trace_check.py pins
        # spans == number of cycle events).
        with self._lock:
            spans = list(self._spans)
            recorder = {
                "capacity": self.capacity,
                "spans": len(spans),
                "dropped": self.dropped,
                "cycle_seq": self._cycle_seq,
                "explains": len(self._explains),
                "explain_retain": self.explain_retain,
                "explains_dropped": self.explains_dropped,
            }
        events: list[dict[str, Any]] = [
            {"ph": "M", "pid": 1, "tid": 0, "ts": 0,
             "name": "process_name",
             "args": {"name": "netaware-scheduler"}},
            {"ph": "M", "pid": 1, "tid": 1, "ts": 0,
             "name": "thread_name",
             "args": {"name": "serving-cycle"}},
        ]
        for s in spans:
            ts = s.t_mono * 1e6
            dur = max(s.dur_s, 0.0) * 1e6
            events.append({
                "name": f"cycle {s.cycle_id} [{s.path}]",
                "cat": "cycle", "ph": "X", "pid": 1, "tid": 1,
                "ts": round(ts, 3), "dur": round(dur, 3),
                "args": s.to_dict(),
            })
            for name, rel, pdur in s.phases:
                pts = ts + max(rel, 0.0) * 1e6
                pend = min(pts + max(pdur, 0.0) * 1e6, ts + dur)
                pts = min(pts, ts + dur)
                events.append({
                    "name": name, "cat": "phase", "ph": "X",
                    "pid": 1, "tid": 1,
                    "ts": round(pts, 3),
                    "dur": round(max(pend - pts, 0.0), 3),
                    "args": {"cycle_id": s.cycle_id},
                })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": dict(self.meta),
            "recorder": recorder,
        }

    def worst_cycle(self) -> CycleSpan | None:
        """The slowest retained cycle — the span a bench artifact must
        ship alongside any claimed p99 number (bench_check Rule 8)."""
        spans = self.spans()
        if not spans:
            return None
        return max(spans, key=lambda s: s.dur_s)

    def crash_dump(self, path: str, reason: str = "shutdown",
                   extra: dict | None = None) -> str:
        """Write the recorder + retained explain records to ``path``
        for post-mortem (SIGTERM / fault path in serve.py; the
        integrity watchdog's stuck-audit dump).  ``extra`` rides along
        verbatim — the watchdog attaches the drift localization so the
        post-mortem names the corrupt rows, not just the cycle.
        Returns the path written.  Best-effort caller-side: exceptions
        propagate so the caller can log-and-continue."""
        doc = {
            "reason": reason,
            "t_wall": time.time(),
            "trace": self.to_chrome_trace(),
            "explains": self.explains(),
        }
        if extra:
            doc["extra"] = extra
        import os

        # serve.py defaults the dump into --checkpoint-dir, which on a
        # first-run shutdown does not exist yet (save_checkpoint only
        # creates it AFTER this post-mortem is written).
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
