"""TPU-native network-aware Kubernetes scheduling framework.

A brand-new implementation of the capabilities of the reference
``pablojara/kubernetesNetAwareScheduler`` (a Go custom scheduler,
``scheduler/scheduler.go``), re-designed TPU-first:

- Cluster telemetry (the reference's per-pod node_exporter scrapes,
  scheduler.go:275-279) lives as columnar matrices in TPU HBM
  (:mod:`~kubernetesnetawarescheduler_tpu.core.state`).
- Node scoring (the reference's min/max weighted vote,
  scheduler.go:334-365) is a batched, vmap'd pod x node x peer cost
  reduction on the MXU (:mod:`~kubernetesnetawarescheduler_tpu.core.score`),
  with feasibility (capacity, taints, affinity) fused in as ``-inf`` masks.
- Assignment (the reference's nondeterministic map-argmax,
  scheduler.go:384-394) is a deterministic argmax with batch-internal
  conflict resolution (:mod:`~kubernetesnetawarescheduler_tpu.core.assign`).
- Scale comes from ``shard_map`` over a device mesh
  (:mod:`~kubernetesnetawarescheduler_tpu.parallel`) and tiled Pallas
  kernels (:mod:`~kubernetesnetawarescheduler_tpu.ops`), not from
  serial HTTP round-trips.
"""

__version__ = "0.1.0"

SCHEDULER_NAME = "netAwareScheduler"  # parity: scheduler.go:119
