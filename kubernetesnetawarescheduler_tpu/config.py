"""Configuration layer.

The reference hardcodes everything: scheduler name (scheduler.go:119), queue
size (scheduler.go:129), node names (scheduler.go:252-256), node IPs
(scheduler.go:275-279), NIC/disk device names (scheduler.go:466-471,
:535-540), iperf file paths (scheduler.go:507-510) and the metric vote
weights 3/2/1/1/3/1 (scheduler.go:360-365).  Here all of that is a real
config surface: dataclasses, loadable from JSON/YAML
(:func:`load_config`), consumed by the JAX scoring service, the benchmark
harness and the native extender shim alike.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Sequence

# ---------------------------------------------------------------------------
# Metric channel layout of the NodeMetrics[N, M] matrix.
#
# The first six channels are exactly the per-node signals the reference
# scrapes and votes on (PrometheusNodeMetrics, scheduler.go:24-32):
#   cpu scaling frequency (getCurrentCPUUsage, scheduler.go:409-442),
#   occupied memory %     (getOccupiedMemoryPercentage, :444-461),
#   tx / rx packet totals (getNetworkPacketsSent/Received, :463-500),
#   iperf3 bandwidth      (getNetworkBandwith, :503-530),
#   disk io in flight     (getDiskIONow, :532-549).
# ---------------------------------------------------------------------------


class Metric:
    """Indices into the metric axis of ``NodeMetrics[N, M]``."""

    CPU_FREQ = 0
    MEM_PCT = 1
    NET_TX = 2
    NET_RX = 3
    BANDWIDTH = 4
    DISK_IO = 5

    COUNT = 6

    NAMES = ("cpu_freq", "mem_pct", "net_tx", "net_rx", "bandwidth", "disk_io")


# Goodness direction per metric: +1 means "higher raw value is better",
# -1 means "lower raw value is better".  Mirrors the reference's sweep
# directions (min for cpu/mem/tx/rx/disk, max for bandwidth;
# scheduler.go:334-359).
GOODNESS = (-1.0, -1.0, -1.0, -1.0, +1.0, -1.0)


class Resource:
    """Indices into the resource axis of capacity/usage/request vectors."""

    CPU = 0
    MEM = 1
    NET_BW = 2

    COUNT = 3

    NAMES = ("cpu", "mem", "net_bw")


@dataclasses.dataclass(frozen=True)
class ScoreWeights:
    """Weights of the scoring policy.

    ``cpu..disk`` reproduce the reference's vote weights (+3 best CPU,
    +2 best memory, +1 best tx, +1 best rx, +3 best bandwidth, +1 best
    disk; scheduler.go:360-365) but applied to *normalized continuous*
    metrics instead of a winner-takes-all vote, so that close seconds
    are not scored identically to the worst node.

    ``peer_bw`` / ``peer_lat`` weight the pod-aware network-cost term —
    the capability the reference's per-pair iperf3 files
    (scheduler.go:503-530) gesture at, generalized to full node x node
    bandwidth / latency matrices.

    ``balance`` is the soft bin-packing penalty (worst-fit resource
    fraction after placement); the reference never consults pod resource
    requests at all (``pod`` is an unused argument of ``prioritize``,
    scheduler.go:248).
    """

    cpu: float = 3.0
    mem: float = 2.0
    net_tx: float = 1.0
    net_rx: float = 1.0
    bandwidth: float = 3.0
    disk: float = 1.0

    peer_bw: float = 2.0
    peer_lat: float = 2.0
    balance: float = 1.0
    # Multiplier on the weighted preferred-affinity score term
    # (``preferredDuringSchedulingIgnoredDuringExecution`` semantics —
    # the mechanism the reference's own probe deployment relied on,
    # netperfScript/deployment.yaml:17-26).  Per-term weights live on
    # the pod (k8s weight scale, 1-100); this scales them into the
    # normalized-score units of the vote/net terms (100 -> 1.0).
    soft_affinity: float = 1.0
    # Penalty per unit of zone skew for soft topology spread
    # (``whenUnsatisfiable: ScheduleAnyway``): nodes in zones already
    # crowded with the pod's group score lower by
    # ``spread * (count[zone] + 1 - min_count)``.
    spread: float = 0.5

    def metric_vector(self) -> tuple[float, ...]:
        """Per-channel weights aligned with :class:`Metric` order."""
        return (self.cpu, self.mem, self.net_tx, self.net_rx,
                self.bandwidth, self.disk)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout for sharded scoring.

    ``dp`` shards the pending-pod axis (batch data-parallelism), ``tp``
    shards the node axis (so the ``N x N`` latency/bandwidth matrices and
    the per-node capacity state split across devices).  ``dp * tp`` must
    equal the number of participating devices.
    """

    dp: int = 1
    tp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.tp


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Static shapes + policy for one compiled scheduler instance.

    Shapes are compile-time constants (XLA requirement): real clusters
    are padded up to ``max_nodes`` / batches padded to ``max_pods`` with
    validity masks, so metric updates never trigger recompilation.
    """

    max_nodes: int = 128
    max_pods: int = 64
    max_peers: int = 8
    # Preferred (soft) affinity terms carried per pod, per bank (one
    # bank of node-label preference terms, one of pod-group preference
    # terms).  Terms beyond this are dropped in declaration order —
    # soft constraints degrade score-neutrally, unlike hard ones.
    max_soft_terms: int = 2
    # Hard ``requiredDuringSchedulingIgnoredDuringExecution``
    # nodeAffinity: up to ``max_ns_terms`` OR'd nodeSelectorTerms per
    # pod, each AND-ing up to ``max_ns_exprs`` matchExpressions
    # (In/NotIn/Exists/DoesNotExist).  Hard constraints degrade CLOSED
    # on overflow (an unrepresentable term/expr can only make the pod
    # harder to place, never easier) — see Encoder._ns_rows.
    max_ns_terms: int = 2
    max_ns_exprs: int = 4
    # Numeric nodeAffinity (Gt/Lt matchExpressions): node label values
    # for up to ``max_numeric_labels`` distinct KEYS are parsed into a
    # dense ``f32[N, L]`` table (NaN = label absent/non-numeric, which
    # fails every comparison — kube's direction), and each
    # nodeSelectorTerm carries up to ``max_ns_num`` (column, lo, hi)
    # comparisons AND'd with its other expressions.  Keys beyond the
    # budget degrade the TERM closed, like every other hard overflow.
    max_numeric_labels: int = 8
    max_ns_num: int = 2
    # Topology domains for topologySpreadConstraints (zone-level:
    # ``topology.kubernetes.io/zone``).  Zones intern on first sight;
    # nodes past the budget fall into an untracked -1 domain where
    # spread constraints cannot see them (degrades, never crashes).
    max_zones: int = 32

    num_metrics: int = Metric.COUNT
    num_resources: int = Resource.COUNT

    # Width (in uint32 words) of every constraint bitmask column
    # (labels / taints / affinity groups).  ``32 * mask_words - 1``
    # distinct keys are assignable per category (the top bit of the
    # last word is the reserved UNKNOWN sentinel), so the default of 4
    # supports 127 distinct selector-referenced labels, taints and pod
    # groups each.  Node labels are interned lazily — only label
    # strings some pod's selector actually references consume a slot —
    # so per-node-unique labels (kubernetes.io/hostname=...) never
    # count against this budget.
    mask_words: int = 4

    weights: ScoreWeights = dataclasses.field(default_factory=ScoreWeights)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)

    # Metric staleness: scores decay toward neutral with age
    # (exp(-age/tau)).  The reference instead re-scrapes every node
    # synchronously per pod (scheduler.go:275-279) and trusts whatever
    # iperf JSON was last dropped into /home (scheduler.go:512).
    staleness_tau_s: float = 60.0

    # Nodes whose staleness confidence exp(-age/tau) has fallen below
    # this floor are excluded from the min/max normalization span: a
    # long-silent node must not stretch the span (making every fresh
    # node look bad) while itself coasting on the neutral 0.5 blend.
    stale_conf_floor: float = 0.05

    # Pending-pod queue capacity; parity with the reference's
    # ``make(chan *v1.Pod, 300)`` (scheduler.go:129).
    queue_capacity: int = 300

    # Pods addressed to this scheduler name are ours (scheduler.go:119,
    # :170).
    scheduler_name: str = "netAwareScheduler"

    # Compute dtype for the score matmuls (MXU-friendly).
    use_bfloat16: bool = True

    # Score kernel for the Score/Filter service path (dispatched via
    # core.pallas_score.score_pods_auto, used by api/extender): "xla"
    # (dense, C[N,N] materialized, best under ~2k nodes) or "pallas"
    # (tiled, lat/bw streamed through VMEM, the 5k-node path;
    # interpreted off-TPU).
    score_backend: str = "xla"

    # Extender webhook micro-batching: a fixed coalescing window in
    # seconds for /filter//prioritize scoring requests.  0 (default) =
    # natural batching only — requests queued while a dispatch is in
    # flight ride the next one, no added latency when idle.  A small
    # positive window (1-5 ms) trades per-request latency for larger
    # shared dispatches on latency-insensitive deployments.
    extender_batch_window_s: float = 0.0

    # Priority preemption: when a pod is unschedulable, evict the
    # cheapest set of strictly-lower-priority pods from the best node
    # and requeue it (core/preempt.py).  Off by default — eviction is
    # a destructive action a deployment must opt into.
    enable_preemption: bool = False

    # Preemption attempts per pod before it is left Pending with a
    # FailedScheduling event (guards against plan/evict/lose loops).
    max_preemption_attempts: int = 2

    # Graceful termination window passed with preemption deletes
    # (DeleteOptions.gracePeriodSeconds; the kubelet gets this long to
    # stop the victim cleanly).
    preemption_grace_s: int = 30

    # How long the preemptor waits for its victims' deletions to be
    # confirmed (watch DELETED -> ledger release) before it is requeued
    # anyway; also the TTL of its node reservation (nominatedNodeName
    # analog) so a wedged victim cannot hold capacity hostage.
    preemption_wait_s: float = 120.0

    # Gang scheduling (core/gang.py): pods annotated with a pod-group
    # are gated until minMember have arrived, scored jointly for
    # intra-gang pairwise bandwidth, and bound all-or-nothing.  On by
    # default because it only engages for pods that carry the
    # annotation — annotation-free workloads pay nothing.
    enable_gang_scheduling: bool = True

    # Default time an incomplete gang may sit gated before its members
    # are released with a FailedScheduling event; a pod-group's own
    # timeout annotation overrides this per gang.
    gang_timeout_s: float = 300.0

    # Strength of the group objective's co-placement bias: the joint
    # scoring pass adds ``gang_weight * mean_j C[n, m_j]`` (the mean
    # net-desirability column over the gang's tentative member nodes)
    # to every member's score row.  0 disables the second pass — gangs
    # still bind atomically but members place independently.
    gang_weight: float = 1.0

    # ---- learned network topology model (netmodel/) ----
    # Off by default: with the model disabled the score/gang path
    # consumes the raw probe matrices bit-identically to a build
    # without the subsystem.
    enable_netmodel: bool = False

    # Vivaldi coordinate dimensionality (latency embedding) and the
    # rank of the bandwidth factorization u[N,r] . v[N,r]^T.
    netmodel_dim: int = 4
    netmodel_rank: int = 8

    # Ring buffer of recent probe observations the Adam step samples
    # from (each probe inserts BOTH directed entries, so the ring holds
    # ring/2 probes), mini-batch size, steps per fit() call and PEAK
    # Adam learning rate (fit() applies an inverse-sqrt decay in total
    # steps, floored at lr/8 — see TopologyModel.fit).  ring >= batch
    # is enforced so a batch never aliases.  The ring must cover the
    # pair set the model is expected to generalize from: at 64
    # probes/cycle the default retains ~9 hours of probes (~1.5 MB
    # host memory); a too-small ring silently caps fit quality
    # (measured at N=1024: an 8192 ring left the log-residual at 0.38
    # where 65536 reaches 0.21).
    netmodel_ring: int = 65536
    netmodel_batch: int = 256
    netmodel_steps: int = 8
    netmodel_lr: float = 0.05

    # Confidence saturation: a node's confidence is
    # 1 - exp(-n_obs / conf_k) — after ~3*conf_k observations the
    # model's estimates for that node count (almost) fully.
    netmodel_conf_k: float = 4.0

    # Probe-freshness horizon for the blend: a pair measured within
    # ~tau seconds keeps its direct probe value; older pairs fade
    # toward the model estimate (weight exp(-age/tau)).
    netmodel_tau_s: float = 600.0

    # Residual monitor: a fresh measurement whose |log1p-bandwidth
    # residual| exceeds this threshold on a pair whose endpoint
    # confidence product is at least resid_conf raises a
    # link-degradation event.  0.7 in log1p space ~= a 2x bandwidth
    # divergence.
    netmodel_resid_threshold: float = 0.7
    netmodel_resid_conf: float = 0.5

    # Share of every probe budget the EIG planner still spends on
    # pure stalest-first exploration (guards against confidently-wrong
    # model regions never being re-measured).
    netmodel_explore_frac: float = 0.25

    # Probe bookkeeping forget horizon (seconds): per-pair last-probe
    # entries older than this are pruned from the orchestrator
    # (bounding its O(N^2) memory); <= 0 means never forget.
    probe_forget_s: float = 0.0

    # ---- control-plane brownout resilience (k8s/kubeclient.py) ----
    # Circuit breaker over API-server health: this many brownout
    # failures (5xx/429/connection errors) within breaker_window_s
    # trips the breaker OPEN; after breaker_cooldown_s it offers
    # HALF-OPEN (one probe).  Open flips the loop into degraded mode:
    # scoring/encode continue, binds park until the probe succeeds.
    breaker_failure_threshold: int = 5
    breaker_window_s: float = 30.0
    breaker_cooldown_s: float = 5.0

    # Shared per-cycle retry pool: ALL API retries in one scheduling
    # cycle draw from this one allowance, bounding the worst-case
    # latency a browned-out API server can inject into a cycle.
    api_retry_budget: int = 8

    # Jittered exponential backoff between retries:
    # min(max, base * 2^attempt) * uniform(0.5, 1.5).
    api_backoff_base_s: float = 0.05
    api_backoff_max_s: float = 2.0

    # ---- incremental device-resident state (core/encode.py,
    # core/score.py, core/loop.py) ----
    # Delta ingest + delta static refresh: the encoder tracks WHICH
    # rows/(i, j) pairs each mutation touched and snapshot() scatter-
    # updates just those indices into the previous device pytree;
    # the assign-static rebuild likewise patches only dirty entries of
    # the prepared N x N desirability pack, keeping the bw/lat
    # normalizers as running extrema (full re-scan only when an
    # extremum-holding entry retreats).  Both paths are bit-identical
    # to a from-scratch rebuild (property-tested); False restores the
    # full-group-transfer/full-rebuild behavior exactly.
    enable_delta_state: bool = True

    # Dirty-fraction escalation threshold: when more than this
    # fraction of a snapshot group's rows (or, for net, N*N pairs) is
    # dirty, upload the whole group instead of scattering — past that
    # point one contiguous transfer beats many scattered ones.
    delta_full_fraction: float = 0.25

    # Off-critical-path assign-static refresh: when True, the serving
    # loop's _static_for never blocks a batch on the O(N^2) static
    # rebuild — batches keep scoring against the last static while a
    # background thread builds the new one.  Off by default: serving
    # output becomes (boundedly) stale-tolerant, which changes
    # placement timing; benches and serve.py opt in explicitly.
    enable_async_static: bool = False

    # Staleness contract for the async refresh: a batch may score
    # against a stale static for at most this many seconds / encoder
    # static_versions, after which _static_for falls back to a
    # synchronous (blocking) rebuild on the serving thread.
    static_max_staleness_s: float = 0.25
    static_max_versions_behind: int = 8

    # Fused winner selection + single-dispatch scheduling step
    # (core/pallas_score.score_winner_tiled, core/score.score_winner,
    # core/assign.fused_schedule_step): the per-batch winner argmax is
    # fused into the score kernel (each pod tile carries a running
    # (best_score, best_node) pair across node tiles instead of
    # writing the P×N score plane to HBM) and the assign+commit pair
    # runs as ONE jitted dispatch with the ClusterState carry donated.
    # Placements are bit-identical to the two-stage path (the fused
    # winner preserves the documented lowest-index tie-break and falls
    # back to score→argmax whenever an out-of-kernel constraint join
    # is active); on by default because it only changes WHERE the
    # reduction runs, never what it computes.
    enable_winner_fusion: bool = True

    # Decision-level tracing (utils/flight.py): ring-buffer capacity of
    # the cycle-span flight recorder (0 disables recording entirely),
    # and the per-pod placement-explain capture.  Explain re-derives the
    # score decomposition host-side AFTER the jitted score/assign ran,
    # so the scoring path stays bit-identical whether it is on or off —
    # it costs extra host work per cycle, hence off by default.
    flight_recorder_size: int = 512
    enable_explain: bool = False
    explain_top_k: int = 5
    explain_retain: int = 512

    # State integrity & self-healing (core/integrity.py): anti-entropy
    # audit period in seconds (0 disables the background auditor; the
    # digest kernel itself costs nothing extra on the hot path — it
    # rides the fused step's donated chain).  The watchdog fires a
    # flight-recorder crash dump after this many CONSECUTIVE audits
    # that detected drift the repair ladder could not clear.
    audit_interval_s: float = 0.0
    audit_watchdog_failures: int = 3

    # Ingest quarantine (ingest/probe.py): a probe result with a
    # non-finite value, negative latency, or non-positive bandwidth is
    # quarantined instead of written into staging; after this many
    # CONSECUTIVE quarantines on one link, a LinkQuarantined Event is
    # raised so operators see the sick path, not just a counter.
    quarantine_streak_events: int = 3

    # Outcome observability (obs/quality.py): join each bound pod's
    # score-time network prediction against subsequently observed
    # probe truth at the maintain cadence — realized bw/lat, regret
    # vs best alternative, calibration residuals.  Observation-only:
    # placements are bit-identical on or off (tests/test_quality.py).
    enable_quality_obs: bool = False
    quality_ring_size: int = 2048
    quality_harvest_interval_s: float = 5.0

    # SLO burn-rate engine (obs/slo.py): declarative objectives
    # evaluated over multi-window burn rates; <= 0 disables an
    # objective.  Targets default to the north-star bars (score p99
    # 5 ms; bind tail from BENCH_r05's measured envelope).  The error
    # budget is the tolerated breach fraction per window; an
    # objective burns when BOTH windows spend budget faster than
    # slo_burn_threshold.
    enable_slo: bool = False
    slo_score_p99_ms: float = 5.0
    slo_bind_p99_ms: float = 1000.0
    slo_regret_ceiling: float = 0.5
    slo_error_budget: float = 0.01
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    slo_burn_threshold: float = 1.0
    slo_eval_interval_s: float = 5.0

    # Continuous rebalancing (core/rebalance.py): a budgeted
    # descheduler that revisits bound pods at maintain cadence,
    # scores current placement vs best feasible alternative on
    # device, and live-migrates the worst offenders through the
    # crash-safe migration ledger.  Hysteresis (minimum relative
    # gain, minimum placement age, per-pod move cooldown) keeps a
    # healthy cluster quiet; the eviction budget and per-group
    # disruption limits bound the blast radius of a storm.
    enable_rebalance: bool = False
    rebalance_interval_s: float = 15.0
    rebalance_min_gain: float = 0.05
    rebalance_min_age_s: float = 60.0
    rebalance_cooldown_s: float = 300.0
    rebalance_max_moves_per_cycle: int = 4
    rebalance_evictions_per_hour: float = 60.0
    rebalance_move_timeout_s: float = 120.0

    # ---- learned scoring policy (policy/) ----
    # Off by default: with the policy disabled, scoring consumes the
    # hand-tuned ScoreWeights constants bit-identically to a build
    # without the subsystem (same discipline as enable_netmodel).
    # When enabled the policy SHADOW-scores first — candidate weights
    # are never promoted into the live scorer without winning the
    # counterfactual-replay gate (policy/replay_eval.py) by at least
    # policy_promote_margin.
    enable_learned_score: bool = False

    # Bounded example ring the Adam step samples from (one example per
    # harvested scheduling decision), mini-batch size, steps per
    # train() call and PEAK learning rate (inverse-sqrt decay in total
    # steps, floored at lr/8 — same schedule as netmodel.fit).
    # ring >= batch so a batch never aliases.
    policy_ring: int = 4096
    policy_batch: int = 128
    policy_steps: int = 4
    policy_lr: float = 0.05

    # Minimum harvested examples before the first train step runs —
    # a near-empty ring would overfit a handful of decisions.
    policy_min_examples: int = 64

    # Maintain-cadence intervals: dataset-harvest + train tick, and
    # the (much rarer) counterfactual evaluation / promotion tick.
    policy_train_interval_s: float = 10.0
    policy_eval_interval_s: float = 120.0

    # Promotion margin: a candidate must beat the incumbent's
    # counterfactual replay outcome (realized-bandwidth-vs-oracle
    # ratio) by at least this much to be promoted.  Below the margin
    # the candidate keeps shadow-scoring and only the disagreement
    # rate is exported.
    policy_promote_margin: float = 0.02

    # Regret tolerance when labeling harvested decisions: an outcome
    # whose quality-observer regret is <= this is treated as "the
    # shipped choice was right"; above it the hindsight-best candidate
    # becomes the training target.
    policy_regret_margin: float = 0.05

    # ---- fleet-of-clusters serving (fleet/) ----
    # Smallest node-count padding bucket the FleetServer packs a
    # tenant into: tenant configs are rounded up to the next
    # power-of-two bucket >= this floor, so many small tenants share
    # ONE jit cache entry instead of each retracing at its exact
    # node count.  Must be a power of two.
    fleet_bucket_min: int = 64

    # ---- persistent multi-cycle serving + coalesced binds (r16) ----
    # Logical cycles per device dispatch: the serving loop encodes a
    # K-wave window once, stages the waves in a device ring, and runs
    # ONE donated scan over all of them — per-dispatch overhead
    # (Python dispatch, launch path, transport on a tunneled chip)
    # amortizes to 1/K of a cycle.  1 = today's per-cycle path,
    # bit-identical by construction.
    multicycle: int = 1
    # Device wave-ring capacity in waves (pre-encoded pod batches
    # staged device-side awaiting the scan).  A window larger than the
    # ring falls back to per-cycle dispatch for the overflow waves and
    # counts it — never drops pods.
    multicycle_queue_depth: int = 4
    # Bind coalescing: how many queued bind batches one worker drain
    # may merge into a single client fanout (1 = off — every batch
    # binds alone, the pre-r16 behavior, bit-identical).
    bind_coalesce_window: int = 1
    # Bound on concurrent bind workers draining the async bind queue
    # (1 = the single pre-r16 worker).  Inflight is capped, never
    # unbounded: the breaker + retry budget still gate every fanout.
    bind_max_inflight: int = 1

    # ---- elastic gang reshaping (r17) ----
    # Off by default: with reshaping disabled (or no gang declaring
    # alternative shapes) placement is bit-identical to the rigid
    # all-or-nothing path — same discipline as enable_rebalance.
    # When enabled, gangs carrying a ``netaware/pod-group-shapes``
    # annotation may commit a SMALLER declared realization when the
    # full shape is infeasible or strictly worse, and the rebalancer
    # may reshape a degraded gang (shrink / regrow / re-tile) through
    # the crash-safe reshape ledger under the same sliding-hour
    # eviction budget as ordinary moves.
    enable_gang_reshaping: bool = False
    # Minimum relative desirability gain (priority-weighted realized
    # intra-gang score under the frozen snapshot) a reshape must clear
    # before any member is evicted — the hysteresis bar that keeps a
    # healthy gang in its current shape.
    reshape_min_gain: float = 0.05
    # Bound on gangs reshaped per rebalancer tick; each member evicted
    # by a reshape is charged against rebalance_evictions_per_hour.
    reshape_max_per_cycle: int = 2

    def __post_init__(self) -> None:
        if self.max_nodes <= 0 or self.max_pods <= 0 or self.max_peers <= 0:
            raise ValueError("shape limits must be positive")
        if self.num_metrics < Metric.COUNT:
            raise ValueError(
                f"need at least {Metric.COUNT} metric channels for parity")
        if self.mask_words <= 0:
            raise ValueError("mask_words must be positive")
        if self.max_ns_terms <= 0 or self.max_ns_exprs <= 0:
            raise ValueError("nodeAffinity term/expr budgets must be "
                             "positive")
        if self.score_backend not in ("xla", "pallas"):
            raise ValueError(
                f"score_backend must be 'xla' or 'pallas', "
                f"got {self.score_backend!r}")
        if self.extender_batch_window_s < 0:
            raise ValueError("extender_batch_window_s must be >= 0")
        if self.gang_timeout_s <= 0:
            raise ValueError("gang_timeout_s must be > 0")
        if self.gang_weight < 0:
            raise ValueError("gang_weight must be >= 0")
        if self.breaker_failure_threshold < 1:
            raise ValueError("breaker_failure_threshold must be >= 1")
        if self.breaker_window_s <= 0 or self.breaker_cooldown_s <= 0:
            raise ValueError("breaker window/cooldown must be > 0")
        if self.api_retry_budget < 0:
            raise ValueError("api_retry_budget must be >= 0")
        if (self.api_backoff_base_s <= 0
                or self.api_backoff_max_s < self.api_backoff_base_s):
            raise ValueError("api backoff must satisfy "
                             "0 < base <= max")
        if self.netmodel_dim < 1 or self.netmodel_rank < 1:
            raise ValueError("netmodel dim/rank must be >= 1")
        if self.netmodel_batch < 1:
            raise ValueError("netmodel_batch must be >= 1")
        if self.netmodel_ring < self.netmodel_batch:
            raise ValueError("netmodel_ring must be >= netmodel_batch")
        if self.netmodel_steps < 0:
            raise ValueError("netmodel_steps must be >= 0")
        if self.netmodel_lr <= 0:
            raise ValueError("netmodel_lr must be > 0")
        if self.netmodel_conf_k <= 0 or self.netmodel_tau_s <= 0:
            raise ValueError("netmodel conf_k/tau_s must be > 0")
        if self.netmodel_resid_threshold <= 0:
            raise ValueError("netmodel_resid_threshold must be > 0")
        if not 0.0 <= self.netmodel_resid_conf <= 1.0:
            raise ValueError("netmodel_resid_conf must be in [0, 1]")
        if not 0.0 <= self.netmodel_explore_frac <= 1.0:
            raise ValueError("netmodel_explore_frac must be in [0, 1]")
        if self.probe_forget_s < 0:
            raise ValueError("probe_forget_s must be >= 0")
        if not 0.0 < self.delta_full_fraction <= 1.0:
            raise ValueError("delta_full_fraction must be in (0, 1]")
        if self.static_max_staleness_s <= 0:
            raise ValueError("static_max_staleness_s must be > 0")
        if self.static_max_versions_behind < 1:
            raise ValueError("static_max_versions_behind must be >= 1")
        if self.flight_recorder_size < 0:
            raise ValueError("flight_recorder_size must be >= 0")
        if self.explain_top_k < 1:
            raise ValueError("explain_top_k must be >= 1")
        if self.explain_retain < 1:
            raise ValueError("explain_retain must be >= 1")
        if self.audit_interval_s < 0:
            raise ValueError("audit_interval_s must be >= 0")
        if self.audit_watchdog_failures < 1:
            raise ValueError("audit_watchdog_failures must be >= 1")
        if self.quarantine_streak_events < 1:
            raise ValueError("quarantine_streak_events must be >= 1")
        if self.quality_ring_size < 1:
            raise ValueError("quality_ring_size must be >= 1")
        if self.quality_harvest_interval_s <= 0:
            raise ValueError("quality_harvest_interval_s must be > 0")
        if self.slo_error_budget < 0:
            raise ValueError("slo_error_budget must be >= 0")
        if self.slo_fast_window_s <= 0 or self.slo_slow_window_s <= 0:
            raise ValueError("slo windows must be > 0")
        if self.slo_fast_window_s > self.slo_slow_window_s:
            raise ValueError("slo_fast_window_s must be <= "
                             "slo_slow_window_s")
        if self.slo_burn_threshold <= 0:
            raise ValueError("slo_burn_threshold must be > 0")
        if self.slo_eval_interval_s <= 0:
            raise ValueError("slo_eval_interval_s must be > 0")
        if self.rebalance_interval_s <= 0:
            raise ValueError("rebalance_interval_s must be > 0")
        if self.rebalance_min_gain < 0:
            raise ValueError("rebalance_min_gain must be >= 0")
        if self.rebalance_min_age_s < 0:
            raise ValueError("rebalance_min_age_s must be >= 0")
        if self.rebalance_cooldown_s < 0:
            raise ValueError("rebalance_cooldown_s must be >= 0")
        if self.rebalance_max_moves_per_cycle < 0:
            raise ValueError(
                "rebalance_max_moves_per_cycle must be >= 0")
        if self.rebalance_evictions_per_hour < 0:
            raise ValueError(
                "rebalance_evictions_per_hour must be >= 0")
        if self.rebalance_move_timeout_s <= 0:
            raise ValueError("rebalance_move_timeout_s must be > 0")
        if self.policy_batch < 1:
            raise ValueError("policy_batch must be >= 1")
        if self.policy_ring < self.policy_batch:
            raise ValueError("policy_ring must be >= policy_batch")
        if self.policy_steps < 0:
            raise ValueError("policy_steps must be >= 0")
        if self.policy_lr <= 0:
            raise ValueError("policy_lr must be > 0")
        if self.policy_min_examples < 1:
            raise ValueError("policy_min_examples must be >= 1")
        if self.policy_train_interval_s <= 0:
            raise ValueError("policy_train_interval_s must be > 0")
        if self.policy_eval_interval_s <= 0:
            raise ValueError("policy_eval_interval_s must be > 0")
        if self.policy_promote_margin < 0:
            raise ValueError("policy_promote_margin must be >= 0")
        if self.policy_regret_margin < 0:
            raise ValueError("policy_regret_margin must be >= 0")
        if (self.fleet_bucket_min < 1
                or self.fleet_bucket_min & (self.fleet_bucket_min - 1)):
            raise ValueError("fleet_bucket_min must be a power of two")
        if self.multicycle < 1:
            raise ValueError("multicycle must be >= 1")
        if self.multicycle_queue_depth < 1:
            raise ValueError("multicycle_queue_depth must be >= 1")
        if self.bind_coalesce_window < 1:
            raise ValueError("bind_coalesce_window must be >= 1")
        if self.bind_max_inflight < 1:
            raise ValueError("bind_max_inflight must be >= 1")
        if self.reshape_min_gain < 0:
            raise ValueError("reshape_min_gain must be >= 0")
        if self.reshape_max_per_cycle < 0:
            raise ValueError("reshape_max_per_cycle must be >= 0")

    def startup_warnings(
            self, policy_eval_trace: str | None = None) -> list[str]:
        """Config combinations that are VALID but silently weaker than
        they look — returned as explicit WARN lines for serve start
        (r15 satellite; the r14 behavior was a one-line banner aside
        that named no flag).  ``policy_eval_trace`` is the serve-level
        trace path (it lives on the loop, not the config).

        Unlike ``__post_init__`` these never raise: each is a legal
        configuration, just one an operator has regretted before."""
        warns: list[str] = []
        if self.enable_learned_score and not policy_eval_trace:
            warns.append(
                "enable_learned_score is on but no evaluation trace "
                "is configured: the policy trains and shadow-scores "
                "but can NEVER be promoted — the counterfactual-"
                "replay promotion gate needs a seeded scenario "
                "trace.  Pass --policy-eval-trace to enable "
                "promotion.")
        return warns


# ---------------------------------------------------------------------------
# (De)serialization — config files for the service / shim / benchmarks.
# ---------------------------------------------------------------------------


# Nested dataclass fields of SchedulerConfig, by field name.
_NESTED = {"weights": ScoreWeights, "mesh": MeshConfig}


def _from_mapping(cls: Any, data: Mapping[str, Any]) -> Any:
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"unknown {cls.__name__} config keys: {sorted(unknown)}; "
            f"valid keys: {sorted(known)}")
    kwargs: dict[str, Any] = {}
    for name, value in data.items():
        nested = _NESTED.get(name)
        if nested is not None and isinstance(value, Mapping):
            value = _from_mapping(nested, value)
        kwargs[name] = value
    return cls(**kwargs)


def config_from_dict(data: Mapping[str, Any]) -> SchedulerConfig:
    return _from_mapping(SchedulerConfig, data)


def config_to_dict(cfg: SchedulerConfig) -> dict[str, Any]:
    return dataclasses.asdict(cfg)


def load_config(path: str) -> SchedulerConfig:
    """Load a :class:`SchedulerConfig` from a JSON or YAML file."""
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        data = yaml.safe_load(text)
    else:
        data = json.loads(text)
    return config_from_dict(data or {})


def save_config(cfg: SchedulerConfig, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(config_to_dict(cfg), fh, indent=2)
        fh.write("\n")


__all__: Sequence[str] = (
    "Metric",
    "Resource",
    "GOODNESS",
    "ScoreWeights",
    "MeshConfig",
    "SchedulerConfig",
    "config_from_dict",
    "config_to_dict",
    "load_config",
    "save_config",
)
