"""ctypes binding for the native scrape parser (native/parser.cpp).

Loads ``libnetaware_parser.so`` if built (``make -C native``) and falls
back to the pure-Python :class:`~.prometheus.NodeExporterExtractor`
otherwise — same contract, so callers never branch.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterable

from kubernetesnetawarescheduler_tpu.ingest.prometheus import (
    NodeExporterExtractor,
)

_LIB_NAME = "libnetaware_parser.so"


def _find_library() -> str | None:
    override = os.environ.get("NETAWARE_PARSER_LIB")
    if override:
        return override if os.path.exists(override) else None
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidate = os.path.join(here, "native", _LIB_NAME)
    return candidate if os.path.exists(candidate) else None


class NativeExtractor:
    """Drop-in for :class:`NodeExporterExtractor.extract` backed by the
    C++ single-pass parser.  ``bandwidth`` is probe-sourced, as in the
    Python extractor."""

    CHANNELS = ("cpu_freq", "mem_pct", "net_tx", "net_rx", "disk_io")

    def __init__(self, lib_path: str,
                 nic_devices: Iterable[str] = ("eth0", "enp3s0f1", "ens4"),
                 disk_devices: Iterable[str] = ("sda", "mmcblk0", "nvme0n1"),
                 ) -> None:
        self._lib = ctypes.CDLL(lib_path)
        self._fn = self._lib.netaware_parse_scrape
        self._fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_double),
        ]
        self._fn.restype = ctypes.c_int
        self._nics = ",".join(nic_devices).encode()
        self._disks = ",".join(disk_devices).encode()

    def extract(self, body: str) -> dict[str, float]:
        import math

        raw = body.encode("utf-8", errors="replace")
        out = (ctypes.c_double * 5)()
        derived = self._fn(raw, len(raw), self._nics, self._disks, out)
        if derived <= 0:
            return {}
        # Exposition format allows literal NaN samples; filter like the
        # Python extractor so they never poison the score matrix.
        return {k: v for k, v in zip(self.CHANNELS, out)
                if math.isfinite(v)}


def make_extractor(nic_devices: Iterable[str] = ("eth0", "enp3s0f1", "ens4"),
                   disk_devices: Iterable[str] = ("sda", "mmcblk0",
                                                  "nvme0n1")):
    """Native extractor when the library is built, Python fallback
    otherwise."""
    path = _find_library()
    if path is not None:
        try:
            return NativeExtractor(path, nic_devices, disk_devices)
        except OSError:
            pass
    return NodeExporterExtractor(nic_devices, disk_devices)
