"""iperf3 JSON result parsing — full schema parity.

The reference declares the complete iperf3 output schema as Go structs
(``Iperf``/``Start``/``End``/``Stream``/``Interval``/... at
scheduler.go:34-117) and consumes a single leaf:
``End.Streams[0].Receiver.BitsPerSecond`` (scheduler.go:528).  This
module mirrors that schema as dataclasses (tolerant of missing
optional fields, as iperf3 omits ``socket``/``retransmits``/... in
some modes) and exposes the same headline extraction plus the richer
quantities the probe pipeline wants (sender/receiver rates, retransmits,
CPU utilization, per-interval series).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Sequence


@dataclasses.dataclass(frozen=True)
class StreamEnd:
    """One direction of a finished stream (``sum_sent``/``sum_received``
    shape; scheduler.go:62-72)."""

    start: float = 0.0
    end: float = 0.0
    seconds: float = 0.0
    bytes: int = 0
    bits_per_second: float = 0.0
    retransmits: int | None = None
    snd_cwnd: int | None = None
    socket: int | None = None

    @classmethod
    def from_dict(cls, d: Mapping[str, Any] | None) -> "StreamEnd":
        d = d or {}
        return cls(
            start=float(d.get("start", 0.0)),
            end=float(d.get("end", 0.0)),
            seconds=float(d.get("seconds", 0.0)),
            bytes=int(d.get("bytes", 0)),
            bits_per_second=float(d.get("bits_per_second", 0.0)),
            retransmits=d.get("retransmits"),
            snd_cwnd=d.get("snd_cwnd"),
            socket=d.get("socket"),
        )


@dataclasses.dataclass(frozen=True)
class CpuUtilization:
    """``end.cpu_utilization_percent`` (scheduler.go:48-55)."""

    host_total: float = 0.0
    host_user: float = 0.0
    host_system: float = 0.0
    remote_total: float = 0.0
    remote_user: float = 0.0
    remote_system: float = 0.0

    @classmethod
    def from_dict(cls, d: Mapping[str, Any] | None) -> "CpuUtilization":
        d = d or {}
        return cls(**{f.name: float(d.get(f.name, 0.0))
                      for f in dataclasses.fields(cls)})


@dataclasses.dataclass(frozen=True)
class IperfResult:
    """The subset of a parsed iperf3 run the scheduler consumes, plus
    provenance."""

    title: str
    protocol: str
    duration_s: float
    sender: StreamEnd
    receiver: StreamEnd
    sum_sent: StreamEnd
    sum_received: StreamEnd
    cpu: CpuUtilization
    intervals_bps: tuple[float, ...] = ()

    @property
    def bandwidth_bps(self) -> float:
        """The reference's headline value:
        ``End.Streams[0].Receiver.BitsPerSecond`` (scheduler.go:528)."""
        return self.receiver.bits_per_second


def parse_iperf_json(text: str | bytes) -> IperfResult:
    """Parse a full iperf3 ``-J`` output document.

    Raises ``ValueError`` on structurally unusable documents (no
    ``end`` section) — the failure mode the reference hits as a nil
    pointer after ``println``-ing the open error (scheduler.go:512-525).
    """
    return iperf_result_from_doc(json.loads(text))


def iperf_result_from_doc(doc: Mapping[str, Any]) -> IperfResult:
    """:func:`parse_iperf_json` for an already-decoded document (the
    probe agent returns iperf3's JSON embedded in its own response —
    no reason to re-serialize it just to re-parse)."""
    end = doc.get("end")
    if not isinstance(end, dict):
        raise ValueError("iperf3 document has no 'end' section")
    streams: Sequence[Mapping[str, Any]] = end.get("streams") or []
    first = streams[0] if streams else {}
    start = doc.get("start") or {}
    test_start = start.get("test_start") or {}
    intervals = tuple(
        float((iv.get("sum") or {}).get("bits_per_second", 0.0))
        for iv in doc.get("intervals") or ())
    return IperfResult(
        title=str(doc.get("title", "")),
        protocol=str(test_start.get("protocol", "")),
        duration_s=float(test_start.get("duration", 0.0)),
        sender=StreamEnd.from_dict(first.get("sender")),
        receiver=StreamEnd.from_dict(first.get("receiver")),
        sum_sent=StreamEnd.from_dict(end.get("sum_sent")),
        sum_received=StreamEnd.from_dict(end.get("sum_received")),
        cpu=CpuUtilization.from_dict(end.get("cpu_utilization_percent")),
        intervals_bps=intervals,
    )


def synth_iperf_json(bits_per_second: float, title: str = "",
                     duration_s: float = 10.0) -> str:
    """A minimal structurally-valid iperf3 ``-J`` document (test +
    fake-probe helper)."""
    stream = {
        "start": 0, "end": duration_s, "seconds": duration_s,
        "bytes": int(bits_per_second * duration_s / 8),
        "bits_per_second": bits_per_second,
    }
    return json.dumps({
        "title": title,
        "start": {"test_start": {"protocol": "TCP",
                                 "duration": duration_s}},
        "intervals": [{"sum": dict(stream)}],
        "end": {
            "streams": [{"sender": dict(stream, retransmits=0),
                         "receiver": dict(stream)}],
            "sum_sent": dict(stream, retransmits=0),
            "sum_received": dict(stream),
            "cpu_utilization_percent": {
                "host_total": 1.0, "host_user": 0.5, "host_system": 0.5,
                "remote_total": 1.0, "remote_user": 0.5,
                "remote_system": 0.5},
        },
    })
