"""Telemetry ingestion: node-exporter scraping, iperf3 parsing, probes.

The reference's ingestion is 5 synchronous HTTP scrapes *inside the
scheduling cycle* (scheduler.go:275-279), fragile substring slicing of
the Prometheus text format (scheduler.go:409-549), and iperf3 JSON
files dropped into ``/home`` by an out-of-band ``kubectl cp`` loop
(netperfScript/run.sh:12-14).  Here ingestion is asynchronous and
structured: a real text-format parser, a full iperf3 schema, a scrape
pool with failure tolerance, and a probe orchestrator maintaining the
pairwise latency/bandwidth matrices.
"""

from kubernetesnetawarescheduler_tpu.ingest.prometheus import (  # noqa: F401
    NodeExporterExtractor,
    parse_prometheus_text,
)
from kubernetesnetawarescheduler_tpu.ingest.iperf import (  # noqa: F401
    IperfResult,
    parse_iperf_json,
)
from kubernetesnetawarescheduler_tpu.ingest.probe import (  # noqa: F401
    FakeProber,
    ProbeOrchestrator,
)
from kubernetesnetawarescheduler_tpu.ingest.scraper import (  # noqa: F401
    ScrapePool,
)
