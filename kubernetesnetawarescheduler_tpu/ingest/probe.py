"""Probe orchestrator: maintains the pairwise latency/bandwidth matrices.

The reference's probe pipeline is a shell loop (netperfScript/script.sh)
that every 60 s runs iperf3 from each node to ONE central server and
drops the JSON into the scheduler pod (run.sh:3-15) — so it measures
each node's path to the server, not node-to-node, and the scheduler
trusts whatever file was last dropped (scheduler.go:512).

Here the orchestrator measures *pairs* on a budgeted round-robin (full
N x N sweeps are O(N^2) probes — at 5k nodes that's 25M pairs, so each
cycle probes the stalest ``budget`` pairs), writes results into the
:class:`~..core.encode.Encoder` staging matrices, and tracks per-pair
staleness.  The prober itself is pluggable:

- :class:`FakeProber` — returns ground truth + noise (tests/bench);
- :class:`Iperf3Prober` — shells out to real iperf3 clients, parsing
  results with :func:`~.iperf.parse_iperf_json` (requires a live
  fleet; excluded from CI).
"""

from __future__ import annotations

import heapq
import subprocess
from typing import Protocol, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.core.encode import Encoder
from kubernetesnetawarescheduler_tpu.ingest.iperf import parse_iperf_json


class Prober(Protocol):
    def probe(self, a: str, b: str) -> tuple[float | None, float | None]:
        """Measure (lat_ms, bw_bps) between two nodes; ``None`` means
        "this prober has no figure for that quantity" (it is left
        untouched for another prober).  Raises on failure."""
        ...


class FakeProber:
    """Ground-truth matrices + multiplicative noise + injectable
    failures (SURVEY.md 5's fault-injection mode).

    ``asymmetry`` > 0 gives every directed pair a fixed, seeded
    multiplicative skew (A->B vs B->A bandwidth differ), and
    ``drift`` > 0 applies a seeded per-link random walk advanced by
    :meth:`advance` — both exercise the topology model's tracking
    behaviour.  Both default to 0 and draw from their own offset-seeded
    generators, so the default configuration consumes the main RNG
    stream identically to before (bit-identical probes for existing
    tests)."""

    def __init__(self, names: Sequence[str], lat_ms: np.ndarray,
                 bw_bps: np.ndarray, noise: float = 0.02,
                 fail_fraction: float = 0.0, seed: int = 0,
                 asymmetry: float = 0.0, drift: float = 0.0) -> None:
        self._index = {n: i for i, n in enumerate(names)}
        self._lat = lat_ms
        self._bw = bw_bps
        self._noise = noise
        self._fail_fraction = fail_fraction
        self._rng = np.random.default_rng(seed)
        self._asymmetry = float(asymmetry)
        self._drift_scale = float(drift)
        self.calls = 0
        n = len(self._index)
        if self._asymmetry:
            # Fixed antisymmetric skew in log space: A->B gets
            # exp(+s), B->A gets exp(-s) — same seed, same skew.
            arng = np.random.default_rng(seed + 1_000_003)
            s = arng.standard_normal((n, n)).astype(np.float64)
            self._asym = np.exp(self._asymmetry * (np.triu(s, 1)
                                                   - np.triu(s, 1).T))
        else:
            self._asym = None
        if self._drift_scale:
            self._drift_rng = np.random.default_rng(seed + 2_000_003)
            self._drift = np.zeros((n, n), np.float64)
        else:
            self._drift_rng = None
            self._drift = None

    def advance(self, steps: int = 1) -> None:
        """Advance the seeded symmetric per-link bandwidth random walk
        (no-op unless constructed with ``drift > 0``)."""
        if self._drift is None:
            return
        n = self._drift.shape[0]
        for _ in range(steps):
            step = self._drift_rng.standard_normal((n, n))
            step = np.triu(step, 1)
            self._drift += self._drift_scale * (step + step.T)

    def probe(self, a: str, b: str) -> tuple[float, float]:
        self.calls += 1
        if self._fail_fraction and self._rng.random() < self._fail_fraction:
            raise TimeoutError(f"probe {a}->{b} timed out")
        i, j = self._index[a], self._index[b]
        f = 1.0 + self._noise * float(self._rng.standard_normal())
        bw = float(self._bw[i, j])
        if self._asym is not None:
            bw *= float(self._asym[i, j])
        if self._drift is not None:
            bw *= float(np.exp(self._drift[i, j]))
        return float(self._lat[i, j] * f), bw / max(f, 0.5)


class Iperf3Prober:
    """LOCAL iperf3 probe: runs ``iperf3 -c <host_of[b]> -J`` from
    *this process* (the flags the reference uses at run.sh:12, minus
    the ``kubectl exec`` transport).

    Vantage caveat: because the client runs wherever the orchestrator
    runs, this measures the orchestrator→b path, NOT a↔b — fine for a
    single-host lab or when the orchestrator is on the only traffic
    source, wrong for a pairwise fleet matrix.  Real deployments use
    :class:`AgentProber`, which delegates the client role to node a's
    probe agent (run.sh's client-side semantics, without kubectl)."""

    def __init__(self, host_of: dict[str, str], duration_s: int = 2) -> None:
        self._host_of = host_of
        self._duration = duration_s

    def probe(self, a: str, b: str) -> tuple[None, float]:
        target = self._host_of[b]
        out = subprocess.run(
            ["iperf3", "-c", target, "-J", "-Z", "-t", str(self._duration),
             "-T", f"probe {a}->{b}"],
            capture_output=True, timeout=self._duration + 10, check=True)
        result = parse_iperf_json(out.stdout)
        # iperf3 has no latency figure: return None so a ping-based
        # prober's latency for the pair is preserved, not zeroed.
        return None, result.bandwidth_bps


def _bracketed(host: str) -> str:
    """IPv6 literals need brackets in a URL netloc."""
    if ":" in host and not host.startswith("["):
        return f"[{host}]"
    return host


class AgentProber:
    """Honest pairwise probe via the per-node probe agent
    (:mod:`~.probe_agent`, deployed by deploy/probes.yaml).

    ``probe(a, b)`` asks node **a**'s agent to run iperf3 against node
    **b**'s iperf3 server and to measure TCP-connect latency — so the
    recorded ``lat[a, b]``/``bw[a, b]`` is the actual a↔b path, the
    client-side vantage the reference got from ``kubectl exec`` into
    per-node client pods (run.sh:12-14), without exec or file drops.

    ``token``, when set, is sent as the ``X-Netaware-Token`` header the
    agent's ``--token`` mode requires (the auth replacing kubectl
    exec's RBAC gate)."""

    def __init__(self, host_of: dict[str, str],
                 agent_port: int = 9798, iperf_port: int = 5201,
                 duration_s: int = 2, timeout_s: float | None = None,
                 token: str = "") -> None:
        self._host_of = host_of
        self._agent_port = agent_port
        self._iperf_port = iperf_port
        self._duration = duration_s
        self._timeout = timeout_s if timeout_s is not None \
            else duration_s + 15.0
        self._token = token

    def probe(self, a: str, b: str) -> tuple[float | None, float]:
        import json as _json
        import urllib.parse
        import urllib.request

        from kubernetesnetawarescheduler_tpu.ingest.iperf import (
            iperf_result_from_doc,
        )

        host_a, host_b = self._host_of[a], self._host_of[b]
        query = urllib.parse.urlencode({
            "target": host_b, "duration": self._duration,
            "port": self._iperf_port})
        url = (f"http://{_bracketed(host_a)}:{self._agent_port}"
               f"/probe?{query}")
        req = urllib.request.Request(url)
        if self._token:
            req.add_header("X-Netaware-Token", self._token)
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            doc = _json.load(resp)
        if "error" in doc:
            raise RuntimeError(f"agent {a} probing {b}: {doc['error']}")
        bw = iperf_result_from_doc(doc["iperf"]).bandwidth_bps
        lat = doc.get("latency_ms")
        return (float(lat) if lat is not None else None), bw


class ProbeOrchestrator:
    """Budgeted pair probing into an Encoder.

    Pair selection is stalest-first by default; passing a ``planner``
    (e.g. :class:`~..netmodel.EIGProbePlanner`) replaces it with
    expected-information-gain selection (the stalest-first selector is
    still handed to the planner for its exploration share).  A
    ``model`` (:class:`~..netmodel.TopologyModel`) receives every
    successful observation and is re-fit at the end of each cycle.

    ``forget_s`` bounds the per-pair bookkeeping: entries whose last
    probe is older than the horizon are pruned on ``advance_clock``
    (they revert to "never probed" for selection purposes, which is
    exactly how a probe that stale should be treated).  <= 0 keeps
    entries forever (the pre-existing behaviour)."""

    def __init__(self, encoder: Encoder, prober: Prober,
                 names: Sequence[str], planner=None, model=None,
                 forget_s: float = 0.0,
                 quarantine_streak: int = 3) -> None:
        self._encoder = encoder
        self._prober = prober
        self._names = list(names)
        self._planner = planner
        self._model = model
        self._forget_s = float(forget_s)
        self._last_probe: dict[tuple[int, int], float] = {}
        self._clock = 0.0
        self.failures = 0
        self.successes = 0
        self.pruned_total = 0
        # Ingest quarantine: a probe that RETURNS (no exception) but
        # carries a value no sane link produces — NaN/Inf, negative
        # latency, non-positive bandwidth — must not reach staging;
        # update_link would either drop it silently or, worse, a NaN
        # would poison the lat/bw planes and every score using them.
        # Quarantined samples are counted per reason (/metrics:
        # netaware_ingest_quarantined_total{reason=...}), the pair
        # stays stale (same degradation as a probe failure), and a
        # per-link CONSECUTIVE-quarantine streak past the threshold
        # queues a LinkQuarantined event (drain_quarantine_events) so
        # operators see the sick path, not just a counter.
        self.quarantined = {"non_finite": 0, "negative_latency": 0,
                            "non_positive_bandwidth": 0}
        self._quarantine_streak = max(int(quarantine_streak), 1)
        self._streaks: dict[tuple[int, int], int] = {}
        self._quarantine_events: list[dict] = []

    def advance_clock(self, dt_s: float) -> None:
        self._clock += dt_s
        if self._model is not None:
            self._model.advance_clock(dt_s)
        if self._forget_s > 0:
            horizon = self._clock - self._forget_s
            stale = [p for p, t in self._last_probe.items() if t < horizon]
            for p in stale:
                del self._last_probe[p]
            self.pruned_total += len(stale)

    def _stalest_pairs(self, budget: int) -> list[tuple[int, int]]:
        # O(P log budget) selection over a generator — never
        # materializes or fully sorts the O(N^2) pair set (12.5M pairs
        # at the 5k-node design point).
        n = len(self._names)
        pairs = ((i, j) for i in range(n) for j in range(i + 1, n))
        return heapq.nsmallest(
            budget, pairs, key=lambda p: self._last_probe.get(p, -np.inf))

    def _select_pairs(self, budget: int) -> list[tuple[int, int]]:
        if self._planner is not None:
            return self._planner.select_pairs(
                len(self._names), budget, self._stalest_pairs)
        return self._stalest_pairs(budget)

    def run_cycle(self, budget: int = 64, fit: bool = True) -> int:
        """Probe the selected ``budget`` pairs; returns successes.
        Failures are counted and skipped — the pair just stays stale
        (no crash, unlike the reference's nil-body read,
        scheduler.go:397-405)."""
        done = 0
        for i, j in self._select_pairs(budget):
            a, b = self._names[i], self._names[j]
            try:
                lat_ms, bw_bps = self._prober.probe(a, b)
            except Exception as exc:
                self.failures += 1
                if self.failures == 1:
                    # First failure EVER gets a log line with the
                    # actual error — a misconfigured fleet (no agents,
                    # wrong port) otherwise looks like quietly-stale
                    # matrices; later failures only count (a pair
                    # staying stale is the designed degradation).
                    import sys

                    print(f"WARNING: first probe failure {a}->{b}: "
                          f"{exc!r} (further failures counted "
                          "silently)", file=sys.stderr)
                continue
            reason = self._validate(lat_ms, bw_bps)
            if reason is not None:
                self._quarantine(i, j, a, b, reason, lat_ms, bw_bps)
                continue
            self._streaks.pop((i, j), None)
            self._encoder.update_link(a, b, lat_ms=lat_ms, bw_bps=bw_bps)
            if self._model is not None:
                ia = self._encoder.node_slot(a)
                ib = self._encoder.node_slot(b)
                if ia is not None and ib is not None:
                    self._model.observe(ia, ib, lat_ms, bw_bps, self._clock)
            self._last_probe[(i, j)] = self._clock
            self.successes += 1
            done += 1
        if done and fit and self._model is not None:
            if self._model.fit():
                # Fresh model params change the blended snapshot even
                # with no new direct probe on a given pair.
                self._encoder.touch_net()
        return done

    @staticmethod
    def _validate(lat_ms: float | None,
                  bw_bps: float | None) -> str | None:
        """Range-check one probe result; returns the quarantine reason
        or ``None`` when the sample is admissible.  A ``None`` quantity
        is the Prober protocol's "no figure from this prober" (e.g.
        iperf3 has no latency) — not a bad sample, so only the
        quantities actually measured are validated."""
        if lat_ms is not None:
            if not np.isfinite(lat_ms):
                return "non_finite"
            if lat_ms < 0:
                return "negative_latency"
        if bw_bps is not None:
            if not np.isfinite(bw_bps):
                return "non_finite"
            if bw_bps <= 0:
                return "non_positive_bandwidth"
        return None

    def _quarantine(self, i: int, j: int, a: str, b: str,
                    reason: str, lat_ms: float, bw_bps: float) -> None:
        self.quarantined[reason] += 1
        streak = self._streaks.get((i, j), 0) + 1
        self._streaks[(i, j)] = streak
        if streak == self._quarantine_streak:
            # Exactly-at-threshold, not >=: one event per sick episode,
            # re-armed when a good sample clears the streak.
            self._quarantine_events.append({
                "link": (a, b), "reason": reason, "streak": streak,
                "lat_ms": None if lat_ms is None else float(lat_ms),
                "bw_bps": None if bw_bps is None else float(bw_bps)})

    def drain_quarantine_events(self) -> list[dict]:
        """Pop the pending over-threshold quarantine streaks — serve.py
        turns each into a ``LinkQuarantined`` k8s Event."""
        out, self._quarantine_events = self._quarantine_events, []
        return out

    def staleness(self) -> dict[str, float]:
        """Aggregate staleness stats — O(tracked pairs) time, O(1)
        output (the old O(N^2) per-pair dict is
        :meth:`staleness_pairs`)."""
        n = len(self._names)
        total = n * (n - 1) // 2
        ages = [self._clock - t for t in self._last_probe.values()]
        return {
            "tracked_pairs": float(len(ages)),
            "total_pairs": float(total),
            "coverage_fraction": (len(ages) / total) if total else 0.0,
            "mean_age_s": float(np.mean(ages)) if ages else float("nan"),
            "max_age_s": float(np.max(ages)) if ages else float("nan"),
        }

    def staleness_pairs(self) -> dict[tuple[str, str], float]:
        """Per-pair ages keyed by name pair.  O(N^2) worst case — debug
        / small-cluster use only; prefer :meth:`staleness`."""
        return {
            (self._names[i], self._names[j]): self._clock - t
            for (i, j), t in self._last_probe.items()}
