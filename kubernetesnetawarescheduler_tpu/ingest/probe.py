"""Probe orchestrator: maintains the pairwise latency/bandwidth matrices.

The reference's probe pipeline is a shell loop (netperfScript/script.sh)
that every 60 s runs iperf3 from each node to ONE central server and
drops the JSON into the scheduler pod (run.sh:3-15) — so it measures
each node's path to the server, not node-to-node, and the scheduler
trusts whatever file was last dropped (scheduler.go:512).

Here the orchestrator measures *pairs* on a budgeted round-robin (full
N x N sweeps are O(N^2) probes — at 5k nodes that's 25M pairs, so each
cycle probes the stalest ``budget`` pairs), writes results into the
:class:`~..core.encode.Encoder` staging matrices, and tracks per-pair
staleness.  The prober itself is pluggable:

- :class:`FakeProber` — returns ground truth + noise (tests/bench);
- :class:`Iperf3Prober` — shells out to real iperf3 clients, parsing
  results with :func:`~.iperf.parse_iperf_json` (requires a live
  fleet; excluded from CI).
"""

from __future__ import annotations

import heapq
import subprocess
from typing import Protocol, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.core.encode import Encoder
from kubernetesnetawarescheduler_tpu.ingest.iperf import parse_iperf_json


class Prober(Protocol):
    def probe(self, a: str, b: str) -> tuple[float | None, float | None]:
        """Measure (lat_ms, bw_bps) between two nodes; ``None`` means
        "this prober has no figure for that quantity" (it is left
        untouched for another prober).  Raises on failure."""
        ...


class FakeProber:
    """Ground-truth matrices + multiplicative noise + injectable
    failures (SURVEY.md 5's fault-injection mode)."""

    def __init__(self, names: Sequence[str], lat_ms: np.ndarray,
                 bw_bps: np.ndarray, noise: float = 0.02,
                 fail_fraction: float = 0.0, seed: int = 0) -> None:
        self._index = {n: i for i, n in enumerate(names)}
        self._lat = lat_ms
        self._bw = bw_bps
        self._noise = noise
        self._fail_fraction = fail_fraction
        self._rng = np.random.default_rng(seed)
        self.calls = 0

    def probe(self, a: str, b: str) -> tuple[float, float]:
        self.calls += 1
        if self._fail_fraction and self._rng.random() < self._fail_fraction:
            raise TimeoutError(f"probe {a}->{b} timed out")
        i, j = self._index[a], self._index[b]
        f = 1.0 + self._noise * float(self._rng.standard_normal())
        return float(self._lat[i, j] * f), float(self._bw[i, j] / max(f, 0.5))


class Iperf3Prober:
    """LOCAL iperf3 probe: runs ``iperf3 -c <host_of[b]> -J`` from
    *this process* (the flags the reference uses at run.sh:12, minus
    the ``kubectl exec`` transport).

    Vantage caveat: because the client runs wherever the orchestrator
    runs, this measures the orchestrator→b path, NOT a↔b — fine for a
    single-host lab or when the orchestrator is on the only traffic
    source, wrong for a pairwise fleet matrix.  Real deployments use
    :class:`AgentProber`, which delegates the client role to node a's
    probe agent (run.sh's client-side semantics, without kubectl)."""

    def __init__(self, host_of: dict[str, str], duration_s: int = 2) -> None:
        self._host_of = host_of
        self._duration = duration_s

    def probe(self, a: str, b: str) -> tuple[None, float]:
        target = self._host_of[b]
        out = subprocess.run(
            ["iperf3", "-c", target, "-J", "-Z", "-t", str(self._duration),
             "-T", f"probe {a}->{b}"],
            capture_output=True, timeout=self._duration + 10, check=True)
        result = parse_iperf_json(out.stdout)
        # iperf3 has no latency figure: return None so a ping-based
        # prober's latency for the pair is preserved, not zeroed.
        return None, result.bandwidth_bps


def _bracketed(host: str) -> str:
    """IPv6 literals need brackets in a URL netloc."""
    if ":" in host and not host.startswith("["):
        return f"[{host}]"
    return host


class AgentProber:
    """Honest pairwise probe via the per-node probe agent
    (:mod:`~.probe_agent`, deployed by deploy/probes.yaml).

    ``probe(a, b)`` asks node **a**'s agent to run iperf3 against node
    **b**'s iperf3 server and to measure TCP-connect latency — so the
    recorded ``lat[a, b]``/``bw[a, b]`` is the actual a↔b path, the
    client-side vantage the reference got from ``kubectl exec`` into
    per-node client pods (run.sh:12-14), without exec or file drops.

    ``token``, when set, is sent as the ``X-Netaware-Token`` header the
    agent's ``--token`` mode requires (the auth replacing kubectl
    exec's RBAC gate)."""

    def __init__(self, host_of: dict[str, str],
                 agent_port: int = 9798, iperf_port: int = 5201,
                 duration_s: int = 2, timeout_s: float | None = None,
                 token: str = "") -> None:
        self._host_of = host_of
        self._agent_port = agent_port
        self._iperf_port = iperf_port
        self._duration = duration_s
        self._timeout = timeout_s if timeout_s is not None \
            else duration_s + 15.0
        self._token = token

    def probe(self, a: str, b: str) -> tuple[float | None, float]:
        import json as _json
        import urllib.parse
        import urllib.request

        from kubernetesnetawarescheduler_tpu.ingest.iperf import (
            iperf_result_from_doc,
        )

        host_a, host_b = self._host_of[a], self._host_of[b]
        query = urllib.parse.urlencode({
            "target": host_b, "duration": self._duration,
            "port": self._iperf_port})
        url = (f"http://{_bracketed(host_a)}:{self._agent_port}"
               f"/probe?{query}")
        req = urllib.request.Request(url)
        if self._token:
            req.add_header("X-Netaware-Token", self._token)
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            doc = _json.load(resp)
        if "error" in doc:
            raise RuntimeError(f"agent {a} probing {b}: {doc['error']}")
        bw = iperf_result_from_doc(doc["iperf"]).bandwidth_bps
        lat = doc.get("latency_ms")
        return (float(lat) if lat is not None else None), bw


class ProbeOrchestrator:
    """Budgeted stalest-pair-first probing into an Encoder."""

    def __init__(self, encoder: Encoder, prober: Prober,
                 names: Sequence[str]) -> None:
        self._encoder = encoder
        self._prober = prober
        self._names = list(names)
        self._last_probe: dict[tuple[int, int], float] = {}
        self._clock = 0.0
        self.failures = 0
        self.successes = 0

    def advance_clock(self, dt_s: float) -> None:
        self._clock += dt_s

    def _stalest_pairs(self, budget: int) -> list[tuple[int, int]]:
        # O(P log budget) selection over a generator — never
        # materializes or fully sorts the O(N^2) pair set (12.5M pairs
        # at the 5k-node design point).
        n = len(self._names)
        pairs = ((i, j) for i in range(n) for j in range(i + 1, n))
        return heapq.nsmallest(
            budget, pairs, key=lambda p: self._last_probe.get(p, -np.inf))

    def run_cycle(self, budget: int = 64) -> int:
        """Probe the ``budget`` stalest pairs; returns successes.
        Failures are counted and skipped — the pair just stays stale
        (no crash, unlike the reference's nil-body read,
        scheduler.go:397-405)."""
        done = 0
        for i, j in self._stalest_pairs(budget):
            a, b = self._names[i], self._names[j]
            try:
                lat_ms, bw_bps = self._prober.probe(a, b)
            except Exception as exc:
                self.failures += 1
                if self.failures == 1:
                    # First failure EVER gets a log line with the
                    # actual error — a misconfigured fleet (no agents,
                    # wrong port) otherwise looks like quietly-stale
                    # matrices; later failures only count (a pair
                    # staying stale is the designed degradation).
                    import sys

                    print(f"WARNING: first probe failure {a}->{b}: "
                          f"{exc!r} (further failures counted "
                          "silently)", file=sys.stderr)
                continue
            self._encoder.update_link(a, b, lat_ms=lat_ms, bw_bps=bw_bps)
            self._last_probe[(i, j)] = self._clock
            self.successes += 1
            done += 1
        return done

    def staleness(self) -> dict[tuple[str, str], float]:
        return {
            (self._names[i], self._names[j]): self._clock - t
            for (i, j), t in self._last_probe.items()}
