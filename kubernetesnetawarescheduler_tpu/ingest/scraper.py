"""Scrape pool: concurrent, failure-tolerant node_exporter ingestion.

The reference scrapes all nodes *serially inside every scheduling
cycle* (5 blocking ``http.Get`` calls per pod scheduled,
scheduler.go:275-279) and crashes on scrape failure (nil body read,
scheduler.go:397-405).  The pool scrapes concurrently on its own
cadence, parses with the real parser, feeds the Encoder, and treats
failure as staleness: a node that stops answering just ages out of the
score (the ``exp(-age/tau)`` decay in
:func:`~..core.score.metric_scores`) and is marked unready after
``unready_after_s``.
"""

from __future__ import annotations

import concurrent.futures
import time
import urllib.request
from typing import Callable, Mapping, Sequence

from kubernetesnetawarescheduler_tpu.core.encode import Encoder
from kubernetesnetawarescheduler_tpu.ingest.prometheus import (
    NodeExporterExtractor,
)

FetchFn = Callable[[str], str]


def http_fetch(url: str, timeout_s: float = 2.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", errors="replace")


class ScrapePool:
    """Scrapes ``targets`` (node name -> metrics URL) into an Encoder.

    ``fetch`` is pluggable for tests (and for transports other than
    plain HTTP :9100, the reference's hardcoded endpoint shape,
    scheduler.go:275-279).
    """

    def __init__(self, encoder: Encoder, targets: Mapping[str, str],
                 fetch: FetchFn = http_fetch,
                 extractor: NodeExporterExtractor | None = None,
                 max_workers: int = 16,
                 unready_after_s: float = 300.0) -> None:
        self._encoder = encoder
        self._targets = dict(targets)
        self._fetch = fetch
        if extractor is None:
            # Native single-pass parser when built, Python fallback.
            from kubernetesnetawarescheduler_tpu.ingest.native import (
                make_extractor,
            )
            extractor = make_extractor()
        self._extractor = extractor
        self._max_workers = max_workers
        self._unready_after_s = unready_after_s
        self._last_success: dict[str, float] = {}
        self._marked_unready: set[str] = set()
        self.failures = 0
        self.successes = 0

    def _scrape_one(self, name: str, url: str) -> tuple[str, dict] | None:
        try:
            body = self._fetch(url)
            return name, self._extractor.extract(body)
        except Exception:
            return None

    def scrape_all(self, now_s: float | None = None) -> int:
        """One concurrent sweep; returns successful scrape count."""
        now = time.monotonic() if now_s is None else now_s
        for name in self._targets:
            # First sighting counts as the baseline, so a node that
            # never answers still ages toward unready.
            self._last_success.setdefault(name, now)
        ok = 0
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self._max_workers) as pool:
            futures = [pool.submit(self._scrape_one, name, url)
                       for name, url in self._targets.items()]
            for fut in concurrent.futures.as_completed(futures):
                result = fut.result()
                if result is None:
                    self.failures += 1
                    continue
                name, channels = result
                self._encoder.update_metrics(name, channels, age_s=0.0)
                self._last_success[name] = now
                self.successes += 1
                ok += 1
                if name in self._marked_unready:
                    # Recovery: only nodes *we* benched come back this
                    # way — a node cordoned via the API stays unready.
                    self._marked_unready.discard(name)
                    self._encoder.mark_ready(name)
        # Nodes silent for too long get marked unready (failure
        # detection — SURVEY.md 5).
        for name, last in self._last_success.items():
            if now - last > self._unready_after_s and \
                    name not in self._marked_unready:
                self._marked_unready.add(name)
                self._encoder.mark_unready(name)
        return ok

    def run_forever(self, period_s: float = 15.0) -> None:
        while True:
            start = time.monotonic()
            self.scrape_all()
            self._encoder.age_metrics(period_s)
            elapsed = time.monotonic() - start
            time.sleep(max(0.0, period_s - elapsed))
