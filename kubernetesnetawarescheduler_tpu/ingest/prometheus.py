"""A real Prometheus text-format parser for node_exporter scrapes.

Replaces the reference's ``strings.Index`` substring slicing
(scheduler.go:409-549), which hardcoded byte offsets (+42, +55, ...),
exactly four CPUs (with an explicit workaround when the master had
eight, scheduler.go:438-439), device names per node class
(``enp3s0f1``/``eth0``, ``sda``/``mmcblk0``; :466-471, :535-540) and
relied on a ``flannel.1`` series appearing right after the wanted one
(:468, :487).

The parser handles the actual exposition format: ``# HELP``/``# TYPE``
comments, ``name{label="value",...} value [timestamp]`` samples, escaped
label values, scientific notation.  The extractor computes the same
derived quantities as the reference (mean CPU scaling frequency over
*all* CPUs, occupied-memory %, per-NIC packet counters, disk io in
flight) without any of the hardcoding.
"""

from __future__ import annotations

import math
import re
from typing import Iterable, Mapping

LabelSet = frozenset[tuple[str, str]]

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r'\s+(?P<value>[^\s]+)'
    r'(?:\s+(?P<ts>[0-9]+))?\s*$')

_LABEL_RE = re.compile(
    r'(?P<key>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<val>(?:\\.|[^"\\])*)"')


_ESCAPES = {"\\": "\\", '"': '"', "n": "\n"}


def _unescape(value: str) -> str:
    # Single pass (sequential str.replace would corrupt e.g. an escaped
    # backslash followed by a literal 'n').
    return re.sub(r"\\(.)",
                  lambda m: _ESCAPES.get(m.group(1), m.group(0)), value)


def parse_prometheus_text(body: str) -> dict[str, dict[LabelSet, float]]:
    """Parse an exposition-format body into
    ``{metric_name: {labelset: value}}``.  Malformed lines are skipped
    (a scrape with junk must degrade, not crash — the reference
    dereferenced a nil response body on error, scheduler.go:397-405)."""
    out: dict[str, dict[LabelSet, float]] = {}
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels_raw = m.group("labels") or ""
        labels = frozenset(
            (lm.group("key"), _unescape(lm.group("val")))
            for lm in _LABEL_RE.finditer(labels_raw))
        out.setdefault(m.group("name"), {})[labels] = value
    return out


class NodeExporterExtractor:
    """Derives the scheduler's metric channels from a parsed scrape.

    ``nic_devices`` / ``disk_devices`` replace the reference's per-node
    hardcoding: any of the listed devices found on the node is summed
    (a node may have several NICs), and overlay devices like
    ``flannel.1`` are simply never listed.
    """

    def __init__(self,
                 nic_devices: Iterable[str] = ("eth0", "enp3s0f1", "ens4"),
                 disk_devices: Iterable[str] = ("sda", "mmcblk0", "nvme0n1"),
                 ) -> None:
        self.nic_devices = frozenset(nic_devices)
        self.disk_devices = frozenset(disk_devices)

    @staticmethod
    def _by_label(samples: Mapping[LabelSet, float], key: str
                  ) -> dict[str, float]:
        out: dict[str, float] = {}
        for labels, value in samples.items():
            for k, v in labels:
                if k == key:
                    out[v] = out.get(v, 0.0) + value
        return out

    def cpu_frequency(self, parsed) -> float:
        """Mean ``node_cpu_scaling_frequency_hertz`` over ALL cpus —
        the reference averaged exactly cpu0..3 and mis-parsed the
        8-core master (scheduler.go:409-442)."""
        samples = parsed.get("node_cpu_scaling_frequency_hertz", {})
        if not samples:
            return 0.0
        return sum(samples.values()) / len(samples)

    def occupied_memory_pct(self, parsed) -> float:
        """``100 - MemAvailable*100/MemTotal`` (scheduler.go:460)."""
        total = parsed.get("node_memory_MemTotal_bytes", {})
        avail = parsed.get("node_memory_MemAvailable_bytes", {})
        t = next(iter(total.values()), 0.0)
        a = next(iter(avail.values()), 0.0)
        if t <= 0:
            return 0.0
        return 100.0 - (a * 100.0 / t)

    def _nic_total(self, parsed, metric: str) -> float:
        per_dev = self._by_label(parsed.get(metric, {}), "device")
        return sum(v for d, v in per_dev.items() if d in self.nic_devices)

    def packets_sent(self, parsed) -> float:
        return self._nic_total(parsed, "node_network_transmit_packets_total")

    def packets_received(self, parsed) -> float:
        return self._nic_total(parsed, "node_network_receive_packets_total")

    def disk_io_now(self, parsed) -> float:
        per_dev = self._by_label(parsed.get("node_disk_io_now", {}), "device")
        return sum(v for d, v in per_dev.items() if d in self.disk_devices)

    def extract(self, body: str) -> dict[str, float]:
        """Scrape body -> metric channels dict (config.Metric names,
        minus ``bandwidth``, which comes from the probe pipeline)."""
        parsed = parse_prometheus_text(body)
        channels = {
            "cpu_freq": self.cpu_frequency(parsed),
            "mem_pct": self.occupied_memory_pct(parsed),
            "net_tx": self.packets_sent(parsed),
            "net_rx": self.packets_received(parsed),
            "disk_io": self.disk_io_now(parsed),
        }
        return {k: v for k, v in channels.items()
                if math.isfinite(v)}
