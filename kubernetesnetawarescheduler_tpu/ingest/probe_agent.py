"""Per-node probe agent: the exec surface for honest pairwise probing.

The reference measured bandwidth *from client pods on each node* via
``kubectl exec iperf3 -c iperf3-server`` (netperfScript/run.sh:12-14) —
client-side semantics, but only against ONE central server, with the
results dropped into the scheduler pod as files.  Round 1 of this build
replaced the file drop but regressed the vantage point: its prober ran
iperf3 *from the scorer pod* to per-node servers, so ``bw[a, b]`` was
really ``bw[scorer, b]``.

This agent restores the client-side vantage WITHOUT kubectl: a tiny
HTTP endpoint that runs in the probe DaemonSet next to the iperf3
server.  ``GET /probe?target=<host>`` makes *this node* run
``iperf3 -c <host> -J`` plus a TCP-connect latency estimate, and
returns both — so the orchestrator's ``AgentProber`` can ask node a's
agent to probe node b and record an honest a↔b measurement.

Stdlib-only (the DaemonSet container just runs
``python -m kubernetesnetawarescheduler_tpu.ingest.probe_agent``);
subprocess args are passed as a list (no shell), and the target is
charset-validated anyway so the agent cannot be steered into running
anything but iperf3 against a host.
"""

from __future__ import annotations

import json
import re
import socket
import subprocess
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse

DEFAULT_AGENT_PORT = 9798
DEFAULT_IPERF_PORT = 5201
MAX_DURATION_S = 30

_TARGET_RE = re.compile(r"^[A-Za-z0-9._:\-]{1,253}$")


def run_iperf3(target: str, duration_s: int, port: int) -> bytes:
    """Run iperf3 client mode against ``target``; returns the raw -J
    output (the same flags the reference used at run.sh:12, minus the
    kubectl transport)."""
    out = subprocess.run(
        ["iperf3", "-c", target, "-p", str(port), "-J", "-Z",
         "-t", str(duration_s)],
        capture_output=True, timeout=duration_s + 10, check=True)
    return out.stdout


def tcp_latency_ms(target: str, port: int, tries: int = 3,
                   timeout_s: float = 2.0) -> float:
    """Median TCP connect time to ``target:port`` in milliseconds —
    the latency figure iperf3 itself does not produce."""
    samples = []
    for _ in range(tries):
        start = time.perf_counter()
        with socket.create_connection((target, port), timeout=timeout_s):
            samples.append((time.perf_counter() - start) * 1e3)
    samples.sort()
    return samples[len(samples) // 2]


def make_handler(runner: Callable[[str, int, int], bytes] = run_iperf3,
                 pinger: Callable[[str, int], float] = tcp_latency_ms,
                 token: str = "",
                 allowed_targets: frozenset[str] | None = None):
    """Handler class factory; ``runner``/``pinger`` are injectable so
    tests exercise the HTTP contract without a live iperf3 fleet.

    An exec surface on a hostPort must not be an open bandwidth-flood
    amplifier (the reference's equivalent, ``kubectl exec``, was
    RBAC-gated): ``token`` requires a matching ``X-Netaware-Token``
    header, and ``allowed_targets`` (when given) restricts probes to
    the known fleet — anything else is rejected before iperf3 runs.
    ``/healthz`` stays open (it reveals nothing and feeds the
    readinessProbe)."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args) -> None:  # quiet; agents are many
            pass

        def _send(self, code: int, doc: dict) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler
            url = urlparse(self.path)
            if url.path == "/healthz":
                self._send(200, {"ok": True})
                return
            if url.path != "/probe":
                self._send(404, {"error": f"unknown path {url.path}"})
                return
            if token and self.headers.get("X-Netaware-Token") != token:
                self._send(403, {"error": "bad or missing token"})
                return
            q = parse_qs(url.query)
            target = (q.get("target") or [""])[0]
            if not _TARGET_RE.match(target):
                self._send(400, {"error": "bad or missing target"})
                return
            if allowed_targets is not None \
                    and target not in allowed_targets:
                self._send(403, {"error": "target not in fleet"})
                return
            try:
                duration = min(int((q.get("duration") or ["2"])[0]),
                               MAX_DURATION_S)
                port = int((q.get("port") or [str(DEFAULT_IPERF_PORT)])[0])
            except ValueError:
                self._send(400, {"error": "bad duration/port"})
                return
            doc: dict = {}
            try:
                doc["latency_ms"] = pinger(target, port)
            except OSError as exc:
                doc["latency_ms"] = None
                doc["latency_error"] = str(exc)
            try:
                doc["iperf"] = json.loads(runner(target, duration, port))
            except (subprocess.SubprocessError, OSError,
                    ValueError) as exc:
                self._send(502, {**doc, "error": f"iperf3 failed: {exc}"})
                return
            self._send(200, doc)

    return Handler


def make_server(port: int = DEFAULT_AGENT_PORT,
                host: str = "0.0.0.0",
                runner: Callable[[str, int, int], bytes] = run_iperf3,
                pinger: Callable[[str, int], float] = tcp_latency_ms,
                token: str = "",
                allowed_targets: frozenset[str] | None = None
                ) -> ThreadingHTTPServer:
    return ThreadingHTTPServer(
        (host, port),
        make_handler(runner, pinger, token=token,
                     allowed_targets=allowed_targets))


def main(argv=None) -> None:
    import argparse
    import os

    ap = argparse.ArgumentParser(description="netaware probe agent")
    ap.add_argument("--port", type=int, default=DEFAULT_AGENT_PORT)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--token", default=os.environ.get(
        "NETAWARE_PROBE_TOKEN", ""),
        help="require X-Netaware-Token on /probe (default: "
             "$NETAWARE_PROBE_TOKEN)")
    ap.add_argument("--allow-targets", default="",
                    help="JSON file: list of hosts (or {name: host} "
                         "map) this agent may probe; unset = any "
                         "charset-valid host (use with --token)")
    args = ap.parse_args(argv)
    allowed = None
    if args.allow_targets:
        with open(args.allow_targets, encoding="utf-8") as fh:
            doc = json.load(fh)
        allowed = frozenset(doc.values() if isinstance(doc, dict)
                            else doc)
    server = make_server(port=args.port, host=args.host,
                         token=args.token, allowed_targets=allowed)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.server_close()


if __name__ == "__main__":
    main()
