"""Million-pod trace-driven scenario engine (ISSUE 14).

Three parts, importable independently:

- :mod:`.generate` — seeded workload generator emitting a replayable
  JSONL event trace (diurnal arrival waves, mixed pod classes,
  heterogeneous node classes, correlated link-degradation bursts,
  node churn) with a versioned header.
- :mod:`.replay` — streaming replay harness driving a trace through
  the REAL serving stack (SchedulerLoop + FakeCluster / chaos proxy)
  at configurable time compression, with bounded memory so millions
  of pods stream without materializing the trace.
- :mod:`.scorecard` — the outcome scorecard: realized bandwidth vs a
  sampled oracle, gang wait time, rebalance disruption, repair
  events, SLO burn windows and p99s — reusing obs/quality's regret
  join and obs/slo's burn math.

Re-exports are LAZY (PEP 562): ``.generate`` and ``.scorecard`` are
numpy-only, and tools/scenario_check.py depends on reaching them
without paying :mod:`.replay`'s jax-backed serving-stack import.
"""

from typing import Any

__all__ = [
    "ScenarioSpec", "TRACE_FORMAT", "TRACE_VERSION",
    "generate_trace", "read_trace",
    "ReplayResult", "replay_trace",
    "build_scorecard", "check_scorecard",
]

_HOME: dict[str, str] = {
    "ScenarioSpec": "generate", "TRACE_FORMAT": "generate",
    "TRACE_VERSION": "generate", "generate_trace": "generate",
    "read_trace": "generate",
    "ReplayResult": "replay", "replay_trace": "replay",
    "build_scorecard": "scorecard", "check_scorecard": "scorecard",
}


def __getattr__(name: str) -> Any:
    mod = _HOME.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)
