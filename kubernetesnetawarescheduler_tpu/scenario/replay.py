"""Streaming scenario replay: drive a generated trace through the
REAL serving stack.

The harness materializes nothing trace-shaped: events stream off disk
(scenario/generate.read_trace), arriving pods buffer only up to one
scheduling wave, committed bindings and API events are consumed
incrementally and truncated (the watermark-compaction the bounded-RSS
acceptance bar measures), and every distribution lands in a bounded
LogHistogram or capped deque.  Millions of pods therefore stream
through a :class:`~...core.loop.SchedulerLoop` — any of the four loop
paths — at CPU-bench shapes.

Virtual time is the trace's ``t`` field.  ``time_compression`` C > 0
paces the replay at C virtual seconds per wall second (sleeping the
difference); C = 0 (default) replays as fast as the loop can serve,
which is what the bench suite wants.  The chaos proxy's virtual clock
is advanced in lockstep, so control-plane fault windows open and
close at trace-relative times regardless of pacing.

With every chaos/drift knob off, replay degenerates to exactly
``add_pods`` + ``run_once`` over :func:`pod_waves` boundaries — the
placement-bit-identity property tests/test_scenario.py pins against a
direct drive of the same pods.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Any, Iterable, Iterator

import numpy as np

from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
    build_fake_cluster,
    feed_metrics,
    sample_metrics,
)
from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    ScoreWeights,
)
from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
from kubernetesnetawarescheduler_tpu.core.state import round_up
from kubernetesnetawarescheduler_tpu.k8s.client import FakeCluster
from kubernetesnetawarescheduler_tpu.k8s.types import Node, Pod
from kubernetesnetawarescheduler_tpu.scenario.generate import (
    pod_from_event,
    read_trace,
    spec_from_json,
)
from kubernetesnetawarescheduler_tpu.utils.timeseries import LogHistogram

#: The suite's bandwidth+latency scoring mix (bench/suite.BW_LAT is
#: not imported to keep scenario -> suite import-free; suite imports
#: scenario for its leg).
REPLAY_WEIGHTS = ScoreWeights(cpu=0.5, mem=0.5, net_tx=0.0, net_rx=0.0,
                              bandwidth=1.0, disk=0.0,
                              peer_bw=3.0, peer_lat=2.0, balance=0.5)


def pod_waves(events: Iterable[dict[str, Any]], batch: int,
              tick_s: float,
              scheduler_name: str = "netAwareScheduler"
              ) -> Iterator[tuple[float, list[Pod]]]:
    """Yield ``(t, pods)`` waves at replay's EXACT flush boundaries
    (wave full, or the event stream crossed a tick bucket), ignoring
    every non-pod event.  This is the public contract the knobs-off
    bit-identity property is stated against: a direct drive feeding
    these waves through a fresh loop must place every pod on the same
    node the full replay harness does."""
    pending: list[Pod] = []
    bucket: int | None = None
    t = 0.0
    for ev in events:
        t = float(ev.get("t", t))
        b = math.floor(t / tick_s)
        if pending and bucket is not None and b != bucket:
            yield t, pending
            pending = []
        bucket = b
        if ev.get("kind") != "pod":
            continue
        pending.append(pod_from_event(ev, scheduler_name))
        if len(pending) >= batch:
            yield t, pending
            pending = []
    if pending:
        yield t, pending


@dataclasses.dataclass
class ReplayResult:
    """Raw outcome material of one replay; scenario/scorecard.py
    compresses it into the published scorecard."""

    pods_streamed: int = 0
    pods_bound: int = 0
    events_consumed: int = 0
    cycles: int = 0
    unschedulable: int = 0
    gangs_seen: int = 0
    gangs_completed: int = 0
    gang_wait_s: list[float] = dataclasses.field(default_factory=list)
    deletes_applied: int = 0
    deletes_failed: int = 0
    link_bursts_applied: int = 0
    link_repairs_applied: int = 0
    node_downs: int = 0
    node_ups: int = 0
    zone_downs: int = 0
    zone_ups: int = 0
    node_upgrades: int = 0
    state_faults: dict[str, int] = dataclasses.field(
        default_factory=dict)
    cycle_ms: LogHistogram = dataclasses.field(
        default_factory=lambda: LogHistogram(
            lo=1e-2, hi=1e5, window=8192))
    slo_samples: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=200_000))
    slo_budget_ms: float = 250.0
    duration_virtual_s: float = 0.0
    duration_wall_s: float = 0.0
    rss_samples: list[int] = dataclasses.field(default_factory=list)
    peak_rss_bytes: int = 0
    active_pods_max: int = 0
    queue_depth_max: int = 0
    rebalance_summary: dict | None = None
    evictions_total: int = 0
    quality_summary: dict | None = None
    invariants: dict | None = None
    sampled_bw: dict | None = None
    placements: dict[str, str] | None = None
    breaker_trips: int = 0
    queue_dropped: int = 0
    integrity: dict | None = None


_PAGE = 4096


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm", encoding="ascii") as fh:
            return int(fh.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


def _build_loop(header: dict[str, Any], batch: int, method: str,
                chaos: bool, queue_capacity: int,
                score_weights: ScoreWeights | None = None,
                reshape: bool = False
                ) -> tuple[SchedulerLoop, SchedulerConfig, FakeCluster,
                           list[Node], np.ndarray, np.ndarray]:
    """The serving stack for a trace header: cluster (optionally
    chaos-proxied), loop, ground-truth matrices, and the node list
    (node_up re-adds need the objects).

    ``score_weights`` substitutes the scoring weight vector for the
    whole replay — the policy promotion gate's counterfactual seam.
    ``None`` keeps :data:`REPLAY_WEIGHTS` exactly (golden-digest
    parity is pinned on this default)."""
    spec = spec_from_json(header["spec"])
    cspec = spec.cluster
    chaos_seed = spec.chaos_seed if chaos else None
    cluster, lat, bw = build_fake_cluster(
        cspec, chaos=chaos_seed)
    inner = cluster.inner if hasattr(cluster, "inner") else cluster
    nodes = list(inner.list_nodes())
    cfg = SchedulerConfig(
        max_nodes=round_up(cspec.num_nodes, 128),
        max_pods=batch,
        max_peers=max(4, spec.max_peers),
        weights=(REPLAY_WEIGHTS if score_weights is None
                 else score_weights),
        queue_capacity=queue_capacity,
        # Built into the loop's cfg from construction: cfg is static
        # to the jitted assigners, so flipping it on a live loop
        # would recompile mid-replay.
        enable_gang_reshaping=reshape,
    )
    loop = SchedulerLoop(cluster, cfg, method=method)
    loop.encoder.set_network(lat, bw)
    feed_metrics(inner, loop.encoder,
                 np.random.default_rng(spec.seed + 1))
    return loop, cfg, cluster, nodes, lat, bw


def replay_trace(path: str, *,
                 batch: int = 64,
                 method: str = "parallel",
                 chaos: bool = True,
                 drift: bool = True,
                 state_faults: bool = True,
                 rebalance: bool = True,
                 reshape: bool = False,
                 quality: bool = True,
                 time_compression: float = 0.0,
                 compact: bool = True,
                 collect_placements: bool = False,
                 oracle_sample: int = 2048,
                 maintain_every: int = 16,
                 slo_budget_ms: float = 250.0,
                 queue_capacity: int = 4096,
                 score_weights: ScoreWeights | None = None,
                 progress: Any = None) -> ReplayResult:
    """Stream the trace at ``path`` through a real SchedulerLoop.

    Knobs mirror the subsystems they gate: ``chaos`` (control-plane
    proxy), ``drift`` (link bursts applied to the encoder's network),
    ``state_faults`` (state_chaos injection), ``rebalance`` (budgeted
    descheduler at maintain cadence), ``reshape`` (elastic gang
    reshaping — requires ``rebalance``; shape-aware placement plus
    degrade-and-recover reshapes through the reshape ledger),
    ``quality`` (outcome observer + harvest).  All off = the
    bit-identity degenerate mode.

    ``collect_placements`` retains the full pod->node map (small
    traces / property tests only — it defeats the bounded-memory
    contract for million-pod runs).

    ``score_weights`` replays the SAME trace under a different
    scoring weight vector (policy/ promotion gate); ``None`` is the
    incumbent :data:`REPLAY_WEIGHTS`, bit-identical to a replay that
    never heard of the override.
    """
    header, events = read_trace(path)
    spec = spec_from_json(header["spec"])
    res = ReplayResult(slo_budget_ms=slo_budget_ms)
    t_wall0 = time.perf_counter()

    loop, cfg, client, nodes, lat0, bw0 = _build_loop(
        header, batch, method, chaos, queue_capacity, score_weights,
        reshape=reshape and rebalance)
    inner = client.inner if hasattr(client, "inner") else client
    node_by_name = {nd.name: nd for nd in nodes}
    node_idx = {nd.name: i for i, nd in enumerate(nodes)}
    metrics_rng = np.random.default_rng(spec.seed + 2)

    if quality:
        from kubernetesnetawarescheduler_tpu.obs.quality import (
            QualityObserver,
        )
        loop.quality = QualityObserver(cfg)
    rb = None
    if rebalance:
        from kubernetesnetawarescheduler_tpu.core.rebalance import (
            Rebalancer,
        )
        rb_cfg = dataclasses.replace(
            cfg,
            enable_rebalance=True,
            rebalance_interval_s=1e-4,
            rebalance_max_moves_per_cycle=32,
            rebalance_evictions_per_hour=512.0,
            rebalance_move_timeout_s=300.0,
            enable_gang_reshaping=bool(reshape),
        )
        rb = Rebalancer(rb_cfg, loop.encoder, loop.client)
        loop.rebalance = rb
    injector = auditor = None
    if state_faults:
        from kubernetesnetawarescheduler_tpu.core.integrity import (
            IntegrityAuditor,
        )
        from kubernetesnetawarescheduler_tpu.core.state_chaos import (
            StateChaosInjector,
        )
        injector = StateChaosInjector(loop.encoder, seed=spec.seed + 3,
                                      loop=loop)
        # Injection without the r10 auditor is not an experiment, it
        # is sabotage: one nan_poison leaves NaN staging that fails
        # every placement FOREVER (measured: a 1M-pod campaign froze
        # at 65k binds at its first fault).  Pair them exactly like
        # serve.py/the r10 soak do, audited at maintain cadence — the
        # window between fault and repair is the realistic blind spot
        # the scorecard's unschedulable spikes then show.
        auditor = IntegrityAuditor(loop.encoder, loop)
        loop.integrity = auditor
        loop.state_chaos = injector

    # Link-drift state: per-node multiplicative degradation factor
    # (bursts can overlap; repair divides its own factor back out).
    deg = np.ones(len(nodes), np.float64)
    degraded_now: set[str] = set()

    def _apply_network() -> None:
        f = np.maximum.outer(deg, deg)
        lat_eff = lat0.astype(np.float64) * f
        bw_eff = bw0.astype(np.float64) / f
        np.fill_diagonal(lat_eff, 0.0)
        np.fill_diagonal(bw_eff, bw_eff.max())
        loop.encoder.set_network(lat_eff, bw_eff)
        return None

    # Gang tracking (bounded by concurrently-active gangs).
    gang_first_t: dict[str, float] = {}
    gang_need: dict[str, int] = {}
    gang_member: dict[str, str] = {}

    # Oracle sampling: one contiguous window starting mid-trace.
    sample_start_t = 0.45 * spec.duration_s
    sampled_pods: list[Pod] = []
    want_placement: dict[str, str] = {}

    placements: dict[str, str] | None = (
        {} if collect_placements else None)

    mark = 0
    ev_mark = 0
    vt = 0.0
    waves = 0
    audit_pending = [False]  # fault injected, repair not yet run

    def _scan_bindings() -> None:
        """Consume newly-committed bindings (watermark), attribute
        gang completions and sampled placements to the current
        virtual time, then truncate the consumed prefix so the list
        never grows with total pod count."""
        nonlocal mark, ev_mark
        blist = loop.client.bindings
        new = blist[mark:]
        mark = len(blist)
        for b in new:
            res.pods_bound += 1
            if placements is not None:
                placements[b.pod_name] = b.node_name
            if b.pod_name in want_placement:
                want_placement[b.pod_name] = b.node_name
            grp = gang_member.pop(b.pod_name, None)
            if grp is not None:
                left = gang_need[grp] - 1
                if left <= 0:
                    res.gangs_completed += 1
                    res.gang_wait_s.append(
                        max(0.0, vt - gang_first_t.pop(grp)))
                    del gang_need[grp]
                else:
                    gang_need[grp] = left
        elist = loop.client.events
        ev_new = elist[ev_mark:]
        ev_mark = len(elist)
        for e in ev_new:
            if e.reason == "FailedScheduling":
                res.unschedulable += 1
        if compact:
            if mark > 8192:
                del blist[:mark]
                mark = 0
            if ev_mark > 8192:
                del elist[:ev_mark]
                ev_mark = 0

    def _cycle() -> None:
        loop.trace_offset = res.events_consumed
        t0 = time.perf_counter()
        loop.run_once(timeout=0.0)
        ms = (time.perf_counter() - t0) * 1e3
        res.cycles += 1
        res.cycle_ms.record(ms)
        res.slo_samples.append((vt, ms > slo_budget_ms))
        _scan_bindings()

    def _flush(wave: list[Pod]) -> None:
        nonlocal waves
        for p in wave:
            if p.pod_group and p.gang_min_member > 1:
                if p.pod_group not in gang_first_t and \
                        p.pod_group not in gang_need:
                    gang_first_t[p.pod_group] = vt
                    gang_need[p.pod_group] = p.gang_min_member
                    res.gangs_seen += 1
                gang_member[p.name] = p.pod_group
            if (vt >= sample_start_t and p.peers
                    and len(sampled_pods) < oracle_sample):
                sampled_pods.append(p)
                want_placement.setdefault(p.name, "")
                for peer in p.peers:
                    want_placement.setdefault(peer, "")
        loop.client.add_pods(wave)
        _cycle()
        # Keep the backlog bounded: the queue is capacity-capped and
        # DROPS on overflow, so a burst bucket must drain before the
        # next wave lands.  Stall guard: pods the loop keeps
        # requeueing (gang-gated under churn, breaker-open brownouts)
        # must not spin this into a busy loop.
        stall = 0
        while len(loop.queue) > 2 * batch and stall < 8:
            before = (loop.scheduled, len(loop.queue))
            _cycle()
            stall = stall + 1 if (loop.scheduled,
                                  len(loop.queue)) == before else 0
        waves += 1
        res.queue_depth_max = max(res.queue_depth_max,
                                  len(loop.queue))
        # A state fault blinds scheduling until repaired; audit on
        # the NEXT wave (≈ the ~1s-interval thread serve.py runs)
        # rather than waiting out the maintain cadence — 16 blind
        # waves is a whole queue-capacity of arrivals.
        if audit_pending[0] and auditor is not None:
            audit_pending[0] = False
            auditor.audit_once()
        if waves % maintain_every == 0:
            _maintain()
        if waves % 32 == 0:
            rss = _rss_bytes()
            res.rss_samples.append(rss)
            res.peak_rss_bytes = max(res.peak_rss_bytes, rss)
            res.active_pods_max = max(res.active_pods_max,
                                      len(inner._pods))
        if progress is not None and waves % 256 == 0:
            progress(res)

    def _maintain() -> None:
        loop.maintain()
        if auditor is not None:
            audit_pending[0] = False
            auditor.audit_once()
        if loop.quality is not None:
            loop.quality.harvest(loop.encoder)
        if rb is not None:
            for name in degraded_now:
                rb.note_link_event(name, "", "degraded", streak=1)
            rb._last_tick = 0.0
            # Same contract as SchedulerLoop._maintain: a chaos
            # transport fault mid-tick is retried next tick, never
            # fatal (moves are crash-safe via the migration ledger).
            try:
                rb.tick(loop)
            except Exception:  # noqa: BLE001 — retried next tick
                pass
        _scan_bindings()

    pending: list[Pod] = []
    bucket: int | None = None
    phase_steady_t = 0.1 * spec.duration_s
    loop.scenario_phase = "warmup"

    for ev in events:
        res.events_consumed += 1
        t = float(ev.get("t", vt))
        if time_compression > 0 and t > vt:
            time.sleep((t - vt) / time_compression)
        if chaos and hasattr(client, "advance") and t > vt:
            client.advance(t - vt)
        vt = max(vt, t)
        if loop.scenario_phase == "warmup" and vt >= phase_steady_t:
            loop.scenario_phase = "steady"
        b = math.floor(t / spec.tick_s)
        kind = ev.get("kind")
        # Bucket boundary: flush (the pod_waves contract).
        if pending and bucket is not None and b != bucket:
            _flush(pending)
            pending = []
        bucket = b

        if kind == "pod":
            pending.append(pod_from_event(ev, cfg.scheduler_name))
            res.pods_streamed += 1
            if len(pending) >= batch:
                _flush(pending)
                pending = []
            continue
        # Non-pod events act on the cluster mid-stream: flush first
        # so their effects land between waves, not inside one.
        if pending:
            _flush(pending)
            pending = []
        if kind == "delete":
            try:
                inner.delete_pod(ev["pod"])
                res.deletes_applied += 1
            except KeyError:
                res.deletes_failed += 1
        elif kind == "link_degrade":
            if drift:
                for name in ev["nodes"]:
                    i = node_idx.get(name)
                    if i is not None:
                        deg[i] *= float(ev["factor"])
                        degraded_now.add(name)
                _apply_network()
                res.link_bursts_applied += 1
        elif kind == "link_repair":
            if drift:
                for name in ev["nodes"]:
                    i = node_idx.get(name)
                    if i is not None:
                        deg[i] /= float(ev["factor"])
                        if abs(deg[i] - 1.0) < 1e-9:
                            deg[i] = 1.0
                            degraded_now.discard(name)
                _apply_network()
                res.link_repairs_applied += 1
        elif kind in ("node_down", "node_upgrade"):
            nd = node_by_name.get(ev["node"])
            if nd is not None and ev["node"] in {
                    x.name for x in inner.list_nodes()}:
                inner.delete_node(ev["node"])
                if kind == "node_upgrade":
                    res.node_upgrades += 1
                else:
                    res.node_downs += 1
        elif kind in ("zone_down", "zone_up"):
            alive = {x.name for x in inner.list_nodes()}
            for name in ev.get("nodes", ()):
                nd = node_by_name.get(name)
                if nd is None:
                    continue
                if kind == "zone_down" and name in alive:
                    inner.delete_node(name)
                elif kind == "zone_up" and name not in alive:
                    inner.add_node(nd)
                    loop.encoder.update_metrics(
                        nd.name, sample_metrics(metrics_rng),
                        age_s=0.0)
            if kind == "zone_down":
                res.zone_downs += 1
            else:
                res.zone_ups += 1
        elif kind == "node_up":
            nd = node_by_name.get(ev["node"])
            if nd is not None and ev["node"] not in {
                    x.name for x in inner.list_nodes()}:
                inner.add_node(nd)
                loop.encoder.update_metrics(
                    nd.name, sample_metrics(metrics_rng), age_s=0.0)
                res.node_ups += 1
        elif kind == "state_fault":
            fault = ev.get("fault", "")
            if injector is not None and fault != "checkpoint_corrupt":
                injector.inject(fault)
                res.state_faults[fault] = (
                    res.state_faults.get(fault, 0) + 1)
                audit_pending[0] = True

    if pending:
        _flush(pending)
    loop.scenario_phase = "drain"
    # Let any open chaos window close before the final drain.
    if chaos and hasattr(client, "advance"):
        client.advance(60.0)
    # Never drain blind: a trailing fault would spin the drain's full
    # cycle budget with every pod unschedulable.
    if audit_pending[0] and auditor is not None:
        audit_pending[0] = False
        auditor.audit_once()
    loop.run_until_drained()
    loop.flush_binds()
    _maintain()
    _scan_bindings()
    rss = _rss_bytes()
    res.rss_samples.append(rss)
    res.peak_rss_bytes = max(res.peak_rss_bytes, rss)

    res.duration_virtual_s = vt
    res.unschedulable = max(res.unschedulable, loop.unschedulable)
    res.queue_dropped = int(getattr(loop.queue, "dropped", 0))
    if loop.breaker is not None:
        res.breaker_trips = getattr(loop.breaker, "trips", 0) or 0
    if rb is not None:
        res.rebalance_summary = dict(rb.summary())
        res.evictions_total = int(
            res.rebalance_summary.get("pods_evicted_total", 0))
    if loop.quality is not None:
        res.quality_summary = dict(loop.quality.summary())
    if auditor is not None:
        res.integrity = {
            "audits": int(auditor.audits_total),
            "drift_detected": int(auditor.drift_detected_total),
            "repairs": dict(auditor.repairs),
            "unrepaired": int(auditor.unrepaired_total),
        }
    if chaos and hasattr(client, "advance"):
        from kubernetesnetawarescheduler_tpu.k8s.chaos import (
            check_invariants,
        )
        res.invariants = check_invariants(loop, inner)

    if sampled_pods:
        res.sampled_bw = _sampled_oracle_bw(
            header, sampled_pods, want_placement, deg, lat0, bw0,
            node_idx, inner, batch, method, queue_capacity)
    res.placements = placements
    loop.stop_bind_worker()
    res.duration_wall_s = time.perf_counter() - t_wall0
    return res


def _sampled_oracle_bw(header: dict[str, Any], sampled: list[Pod],
                       want_placement: dict[str, str],
                       deg: np.ndarray, lat0: np.ndarray,
                       bw0: np.ndarray, node_idx: dict[str, int],
                       inner: FakeCluster, batch: int, method: str,
                       queue_capacity: int) -> dict[str, Any]:
    """Realized traffic-weighted peer bandwidth of the replayed
    placements vs an oracle that schedules the SAME sampled pods
    fresh with full knowledge of the final (drifted) network —
    bounded: the sample is one mid-trace window, edges restricted to
    pairs inside it."""
    f = np.maximum.outer(deg, deg)
    bw_eff = bw0.astype(np.float64) / f
    loopback = float(bw_eff.max())
    alive = {nd.name for nd in inner.list_nodes()}

    # Oracle: fresh loop over the currently-alive fleet, truth = the
    # final effective matrices.
    loop, cfg, client, nodes, _lat, _bw = _build_loop(
        header, batch, method, chaos=False,
        queue_capacity=queue_capacity)
    o_inner = client.inner if hasattr(client, "inner") else client
    # Final truth, restricted to the oracle's own (full) fleet; down
    # nodes score as absent via delete.
    lat_eff = lat0.astype(np.float64) * f
    bwm = bw_eff.copy()
    np.fill_diagonal(lat_eff, 0.0)
    np.fill_diagonal(bwm, bwm.max())
    loop.encoder.set_network(lat_eff, bwm)
    for nd in nodes:
        if nd.name not in alive:
            o_inner.delete_node(nd.name)
    sample_names = {p.name for p in sampled}
    clean = [dataclasses.replace(
        p, node_name="", uid=p.uid + "-oracle",
        peers={q: w for q, w in p.peers.items()
               if q in sample_names})
        for p in sampled]
    for start in range(0, len(clean), batch):
        loop.client.add_pods(clean[start:start + batch])
        loop.run_once(timeout=0.0)
    loop.run_until_drained()
    loop.flush_binds()
    oracle_place = {b.pod_name: b.node_name
                    for b in loop.client.bindings}
    loop.stop_bind_worker()

    def _bw(place: dict[str, str]) -> tuple[float, int]:
        total = 0.0
        edges = 0
        for p in sampled:
            ni = place.get(p.name)
            ii = node_idx.get(ni) if ni else None
            if ii is None:
                continue
            for q, w in p.peers.items():
                if q not in sample_names:
                    continue
                nj = place.get(q)
                jj = node_idx.get(nj) if nj else None
                if jj is None:
                    continue
                total += w * (loopback if ii == jj
                              else float(bw_eff[ii, jj]))
                edges += 1
        return total, edges

    real_bw, real_edges = _bw(want_placement)
    oracle_bw, oracle_edges = _bw(oracle_place)
    ratio = (real_bw / oracle_bw) if oracle_bw > 0 else 1.0
    return {
        "sampled_pods": len(sampled),
        "sampled_edges": real_edges,
        "oracle_edges": oracle_edges,
        "realized_bw": float(real_bw),
        "oracle_bw": float(oracle_bw),
        "realized_bw_ratio_vs_oracle": float(ratio),
    }
