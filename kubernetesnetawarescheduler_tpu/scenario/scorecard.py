"""Outcome scorecard for a scenario replay.

One compact, shape-checked dict answering "did the scheduler do a
good job under this scenario" — not "did it crash".  Reuses the
repo's existing outcome math instead of re-deriving it: SLO burn
windows come from :mod:`~kubernetesnetawarescheduler_tpu.obs.slo`'s
pure functions over the replay's per-cycle breach samples, and
placement-quality regret is lifted straight from the attached
:class:`~kubernetesnetawarescheduler_tpu.obs.quality.QualityObserver`
summary (the truth-join regret the quality leg publishes).

``check_scorecard`` is the single shape lint, shared by
tools/scenario_check.py and the bench_check Rule 13 committed-artifact
gate's test fixtures — a scorecard that passes here renders cleanly
everywhere downstream.
"""

from __future__ import annotations

import math
from typing import Any

from kubernetesnetawarescheduler_tpu.obs.slo import (
    breach_fraction,
    burn_rate,
    is_burning,
)

# NOTE: scenario.replay (the ReplayResult producer) is deliberately
# NOT imported here — build_scorecard takes it duck-typed so this
# module stays jax-free for tools/scenario_check.py.


def _percentile(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    xs = sorted(values)
    # Nearest-rank, matching LogHistogram.percentile's contract.
    idx = min(len(xs) - 1, max(0, math.ceil(q / 100.0 * len(xs)) - 1))
    return float(xs[idx])


def build_scorecard(res: "Any", *,
                    fast_window_s: float = 300.0,
                    slow_window_s: float = 3600.0,
                    error_budget: float = 0.01,
                    burn_threshold: float = 1.0,
                    evictions_per_hour_budget: float = 512.0
                    ) -> dict[str, Any]:
    """Compress a :class:`~.replay.ReplayResult` (duck-typed; see
    module note) into the published scorecard.

    SLO windows are VIRTUAL time (the trace's clock): a 10x-compressed
    replay burns budget at trace-relative rates, same as production
    would.  Budget adherence for the rebalancer is WALL time, because
    that is the clock its own token bucket enforces.
    """
    now = res.duration_virtual_s
    samples = list(res.slo_samples)
    frac, n_window = breach_fraction(samples, now, slow_window_s)
    fast = burn_rate(samples, now, fast_window_s, error_budget)
    slow = burn_rate(samples, now, slow_window_s, error_budget)

    rb = res.rebalance_summary or {}
    wall_h = max(res.duration_wall_s, 1e-9) / 3600.0
    evicted = int(rb.get("pods_evicted_total", res.evictions_total))
    evictions_per_wall_hour = evicted / wall_h
    qs = res.quality_summary or {}

    card: dict[str, Any] = {
        "pods": {
            "streamed": int(res.pods_streamed),
            "bound": int(res.pods_bound),
            "unschedulable": int(res.unschedulable),
            "deletes_applied": int(res.deletes_applied),
            "deletes_failed": int(res.deletes_failed),
            "queue_dropped": int(res.queue_dropped),
            "active_max": int(res.active_pods_max),
        },
        "bandwidth": dict(res.sampled_bw or {}),
        "gangs": {
            "seen": int(res.gangs_seen),
            "completed": int(res.gangs_completed),
            "wait_p50_s": _percentile(res.gang_wait_s, 50.0),
            "wait_p99_s": _percentile(res.gang_wait_s, 99.0),
        },
        "rebalance": {
            "summary": dict(rb),
            "half_moved_gangs": int(rb.get("half_moved_gangs", 0)),
            "pods_evicted_total": evicted,
            "evictions_per_wall_hour": float(evictions_per_wall_hour),
            "evictions_per_hour_budget": float(
                evictions_per_hour_budget),
            # 5% slack: the bucket refills continuously, so a run
            # ending just after a refill can sit a hair over rate.
            "within_budget": bool(
                evictions_per_wall_hour
                <= evictions_per_hour_budget * 1.05),
        },
        "repair_events": {
            "link_bursts": int(res.link_bursts_applied),
            "link_repairs": int(res.link_repairs_applied),
            "node_downs": int(res.node_downs),
            "node_ups": int(res.node_ups),
            "state_faults": dict(res.state_faults),
            # r10 auditor counters (audits/drift_detected/repairs/
            # unrepaired); {} when state-fault injection was off.
            "integrity": dict(getattr(res, "integrity", None) or {}),
            "breaker_trips": int(res.breaker_trips),
        },
        "slo": {
            "budget_ms": float(res.slo_budget_ms),
            "breach_fraction": float(frac),
            "window_samples": int(n_window),
            "fast_burn": float(fast) if math.isfinite(fast) else -1.0,
            "slow_burn": float(slow) if math.isfinite(slow) else -1.0,
            "burning": bool(is_burning(fast, slow, burn_threshold)),
            "fast_window_s": float(fast_window_s),
            "slow_window_s": float(slow_window_s),
            "error_budget": float(error_budget),
        },
        "quality": {
            "regret_p50": float(qs.get("regret_p50", 0.0)),
            "regret_p99": float(qs.get("regret_p99", 0.0)),
            "calibration_samples": int(
                qs.get("calibration_samples", 0)),
        },
        "cycles": {
            "count": int(res.cycles),
            "p50_ms": float(res.cycle_ms.percentile(50.0)),
            "p99_ms": float(res.cycle_ms.percentile(99.0)),
        },
        "memory": {
            "peak_rss_bytes": int(res.peak_rss_bytes),
            "rss_first_bytes": int(
                res.rss_samples[0] if res.rss_samples else 0),
            "rss_last_bytes": int(
                res.rss_samples[-1] if res.rss_samples else 0),
            "samples": int(len(res.rss_samples)),
        },
        "durations": {
            "virtual_s": float(res.duration_virtual_s),
            "wall_s": float(res.duration_wall_s),
        },
    }
    if res.invariants is not None:
        card["invariants"] = {k: int(v)
                              for k, v in res.invariants.items()}
    return card


#: section -> fields that must be present and numeric (bool counts as
#: numeric for the flags; json round-trip keeps these types).
_REQUIRED: dict[str, tuple[str, ...]] = {
    "pods": ("streamed", "bound", "unschedulable"),
    "gangs": ("seen", "completed", "wait_p50_s", "wait_p99_s"),
    "rebalance": ("half_moved_gangs", "pods_evicted_total",
                  "within_budget"),
    "repair_events": ("link_bursts", "link_repairs", "node_downs",
                      "node_ups"),
    "slo": ("budget_ms", "breach_fraction", "fast_burn", "slow_burn",
            "burning"),
    "cycles": ("count", "p50_ms", "p99_ms"),
    "memory": ("peak_rss_bytes",),
    "durations": ("virtual_s", "wall_s"),
}


def check_scorecard(card: Any) -> list[str]:
    """Shape-lint a scorecard dict; returns problems (empty = clean).

    Checks structure and internal consistency, NOT outcome quality —
    a scorecard reporting a terrible run still lints clean; bars live
    in the bench suite."""
    problems: list[str] = []
    if not isinstance(card, dict):
        return ["scorecard: not a dict"]
    for section, fields in _REQUIRED.items():
        sec = card.get(section)
        if not isinstance(sec, dict):
            problems.append(f"scorecard.{section}: missing or not a "
                            "dict")
            continue
        for fld in fields:
            v = sec.get(fld)
            if isinstance(v, bool):
                continue
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                problems.append(
                    f"scorecard.{section}.{fld}: missing or "
                    f"non-finite ({v!r})")
    if problems:
        return problems
    if not isinstance(card.get("bandwidth"), dict):
        problems.append("scorecard.bandwidth: missing or not a dict")
    pods = card["pods"]
    if pods["bound"] > pods["streamed"]:
        problems.append("scorecard.pods: bound exceeds streamed")
    gangs = card["gangs"]
    if gangs["completed"] > gangs["seen"]:
        problems.append("scorecard.gangs: completed exceeds seen")
    if gangs["wait_p99_s"] + 1e-9 < gangs["wait_p50_s"]:
        problems.append("scorecard.gangs: p99 below p50")
    frac = card["slo"]["breach_fraction"]
    if not 0.0 <= frac <= 1.0:
        problems.append("scorecard.slo.breach_fraction out of [0,1]")
    bw = card["bandwidth"]
    ratio = bw.get("realized_bw_ratio_vs_oracle")
    if ratio is not None and (not isinstance(ratio, (int, float))
                              or not math.isfinite(ratio)
                              or ratio < 0.0):
        problems.append(
            "scorecard.bandwidth.realized_bw_ratio_vs_oracle "
            f"invalid ({ratio!r})")
    cyc = card["cycles"]
    if cyc["p99_ms"] + 1e-9 < cyc["p50_ms"]:
        problems.append("scorecard.cycles: p99 below p50")
    return problems
