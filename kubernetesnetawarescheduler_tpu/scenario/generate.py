"""Seeded scenario-trace generator: a replayable cluster "day" as one
compact JSONL event stream.

The trace is the contract between the generator and the replay
harness (scenario/replay.py): line 1 is a versioned header carrying
the full :class:`ScenarioSpec` (so a trace file alone reproduces its
cluster and its own regeneration), every following line is one event
in non-decreasing virtual time:

- ``pod`` — an arrival (serving / batch / gang / long-running class;
  gang members share a ``pod_group`` and arrive together);
- ``delete`` — a departure (the pod's lifetime expired — batch jobs
  finish, serving pods roll; long-running pods never depart, the
  slow-drift class whose outcome quality the scorecard exists for);
- ``link_degrade`` / ``link_repair`` — a CORRELATED burst: every
  link touching one rack's nodes degrades by ``factor`` for the
  burst duration (the k8s chaos proxy models control-plane faults;
  these model data-plane drift);
- ``node_down`` / ``node_up`` — node churn;
- ``zone_down`` / ``zone_up`` — a CORRELATED mass failure (v2): every
  node in one zone goes down at once and comes back together — the
  degrade-and-recover trigger elastic gang reshaping exists for;
- ``node_upgrade`` — one node drained for a rolling upgrade (v2);
  the replay treats it as a node_down whose ``node_up`` is scheduled
  a hold later, batch after batch marching across the fleet;
- ``state_fault`` — one scheduler-state fault class for the
  state_chaos injector (core/state_chaos.py).

Determinism is the whole point: same seed + spec -> byte-identical
file (sorted keys, fixed float formatting, a single rng drawn in a
fixed order; test-enforced).  Generation is STREAMING — a bounded
heap of scheduled future events (departures, repairs) is the only
state that grows with concurrency, never with total pod count.
"""

from __future__ import annotations

import dataclasses
import gzip
import heapq
import io
import json
import math
from typing import Any, Callable, Iterator

import numpy as np

from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
    ClusterSpec,
    NodeClassSpec,
)
from kubernetesnetawarescheduler_tpu.core.state_chaos import (
    STATE_FAULT_CLASSES,
)
from kubernetesnetawarescheduler_tpu.k8s.types import Pod

TRACE_FORMAT = "scenario-trace/v1"
# v2 (r17): zone_down/zone_up + node_upgrade event kinds, elastic
# gang-shape declarations on gang pods.  Readers accept 1..TRACE_VERSION
# — a v1 trace replays unchanged (none of the new kinds appear in it).
TRACE_VERSION = 2

EVENT_KINDS = ("pod", "delete", "link_degrade", "link_repair",
               "node_down", "node_up", "zone_down", "zone_up",
               "node_upgrade", "state_fault")

POD_CLASSES = ("serving", "batch", "gang", "longrun")


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """Everything the generator draws from — embedded verbatim in the
    trace header, so the trace is self-describing and regenerable."""

    seed: int = 0
    duration_s: float = 240.0        # virtual seconds of arrivals
    tick_s: float = 1.0              # arrival bucketing granularity
    base_rate: float = 50.0          # mean pod arrivals per virtual s
    day_s: float = 120.0             # diurnal period (compressed day)
    diurnal_amplitude: float = 0.6   # rate swing around the mean

    # Pod-class mix (fractions of arrivals; remainder = serving).
    batch_fraction: float = 0.3
    gang_fraction: float = 0.1
    longrun_fraction: float = 0.05
    gang_sizes: tuple[int, ...] = (4, 8, 16)

    # Mean lifetimes (virtual seconds, exponential; long-running pods
    # never depart).  A floor of a few ticks keeps departures from
    # racing the pod's own scheduling.
    serving_lifetime_s: float = 90.0
    batch_lifetime_s: float = 30.0
    gang_lifetime_s: float = 60.0
    lifetime_floor_s: float = 5.0

    # Workload shape (mirrors bench/fakecluster.WorkloadSpec).
    services: int = 16
    peer_fraction: float = 0.5
    max_peers: int = 3
    cpu_range: tuple[float, float] = (0.1, 2.0)
    mem_range: tuple[float, float] = (0.2, 4.0)
    netbw_range: tuple[float, float] = (0.05, 1.0)
    gang_cpu: float = 2.0
    gang_mem: float = 4.0
    gang_netbw: float = 0.5

    # Data-plane drift: correlated link-degradation bursts.
    link_burst_rate_per_s: float = 0.0
    link_burst_factor: float = 8.0
    link_burst_duration_s: float = 15.0

    # Node churn.
    node_churn_rate_per_s: float = 0.0
    node_down_duration_s: float = 20.0

    # Zonal outage (v2): at ``zone_outage_at_s`` every node of
    # ``zone_outage_zone`` goes down at once (one zone_down event),
    # returning together after the duration.  Negative = never.
    zone_outage_at_s: float = -1.0
    zone_outage_zone: int = 0
    zone_outage_duration_s: float = 45.0

    # Rolling node upgrade (v2): starting at ``rolling_upgrade_at_s``,
    # nodes drain in batches of ``rolling_upgrade_batch``, each held
    # down ``rolling_upgrade_hold_s`` before the next batch starts.
    # Negative = never.
    rolling_upgrade_at_s: float = -1.0
    rolling_upgrade_batch: int = 4
    rolling_upgrade_hold_s: float = 10.0

    # Fraction of gangs declaring an elastic shape family (v2):
    # "size,size//2:0.5" — full shape preferred, half shape at 0.5
    # priority (core/gang.parse_gang_shapes grammar).  0.0 = every
    # gang rigid, exactly the v1 stream.
    gang_shapes_fraction: float = 0.0

    # Scheduler-state faults (core/state_chaos.py classes).
    state_fault_rate_per_s: float = 0.0

    # Control-plane chaos: a seed for k8s/chaos.ChaosSchedule.generate
    # applied by the replay harness (None = bare cluster).
    chaos_seed: int | None = None

    # The fleet the trace runs on.
    cluster: ClusterSpec = dataclasses.field(
        default_factory=lambda: ClusterSpec(num_nodes=64, seed=0))


# ---------------------------------------------------------------------------
# Spec <-> JSON (tuples and the nested ClusterSpec/NodeClassSpec need
# explicit reconstruction; JSON has no tuple).
# ---------------------------------------------------------------------------


def spec_to_json(spec: ScenarioSpec) -> dict[str, Any]:
    doc = dataclasses.asdict(spec)

    def _tuples(obj: Any) -> Any:
        if isinstance(obj, tuple):
            return [_tuples(v) for v in obj]
        if isinstance(obj, dict):
            return {k: _tuples(v) for k, v in obj.items()}
        return obj

    return _tuples(doc)


def spec_from_json(doc: dict[str, Any]) -> ScenarioSpec:
    doc = dict(doc)
    cluster = dict(doc.pop("cluster"))
    classes = tuple(
        NodeClassSpec(**{
            k: (tuple(v) if isinstance(v, list) else v)
            for k, v in c.items()})
        for c in cluster.pop("node_classes", ()))
    for key in ("cpu_range", "mem_range", "netbw_range"):
        cluster[key] = tuple(cluster[key])
    cluster_spec = ClusterSpec(node_classes=classes, **cluster)
    for key in ("gang_sizes", "cpu_range", "mem_range", "netbw_range"):
        doc[key] = tuple(doc[key])
    return ScenarioSpec(cluster=cluster_spec, **doc)


# ---------------------------------------------------------------------------
# Trace IO.
# ---------------------------------------------------------------------------


def _open_write(path: str) -> io.TextIOBase:
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "wb", compresslevel=5),
                                encoding="utf-8", newline="\n")
    return open(path, "w", encoding="utf-8", newline="\n")


def _open_read(path: str) -> io.TextIOBase:
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"),
                                encoding="utf-8")
    return open(path, encoding="utf-8")


def _dump(obj: dict[str, Any]) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      allow_nan=False)


def read_trace(path: str) -> tuple[dict[str, Any],
                                   Iterator[dict[str, Any]]]:
    """Open a trace: returns ``(header, events)`` where ``events`` is
    a STREAMING iterator over event dicts (the file is never
    materialized).  Raises ValueError on a bad header."""
    fh = _open_read(path)
    line = fh.readline()
    try:
        header = json.loads(line)
    except ValueError as exc:
        fh.close()
        raise ValueError(f"trace header unparseable: {exc}") from exc
    if (not isinstance(header, dict)
            or header.get("format") != TRACE_FORMAT
            or header.get("kind") != "header"):
        fh.close()
        raise ValueError(
            f"not a {TRACE_FORMAT} trace (header {header!r})")
    ver = header.get("version")
    if not isinstance(ver, int) or not 1 <= ver <= TRACE_VERSION:
        fh.close()
        raise ValueError(
            f"trace version {ver!r} outside the supported range "
            f"1..{TRACE_VERSION}")

    def _events() -> Iterator[dict[str, Any]]:
        try:
            for raw in fh:
                if raw.strip():
                    yield json.loads(raw)
        finally:
            fh.close()

    return header, _events()


def pod_from_event(ev: dict[str, Any],
                   scheduler_name: str = "netAwareScheduler") -> Pod:
    """Materialize one ``pod`` event as a schedulable Pod."""
    from kubernetesnetawarescheduler_tpu.core.gang import (
        parse_gang_shapes,
    )

    p = ev["pod"]
    return Pod(
        name=p["name"],
        scheduler_name=scheduler_name,
        requests={"cpu": p["cpu"], "mem": p["mem"],
                  "net_bw": p["net_bw"]},
        peers=dict(p.get("peers", {})),
        group=p.get("group", ""),
        pod_group=p.get("pod_group", ""),
        gang_min_member=int(p.get("gang_min_member", 0)),
        priority=float(p.get("priority", 0.0)),
        gang_shapes=parse_gang_shapes(p.get("gang_shapes", "")),
    )


# ---------------------------------------------------------------------------
# Generation.
# ---------------------------------------------------------------------------


def _diurnal(spec: ScenarioSpec, t: float) -> float:
    """Arrival-rate multiplier at virtual time t (mean 1.0)."""
    return max(0.0, 1.0 + spec.diurnal_amplitude
               * math.sin(2.0 * math.pi * t / spec.day_s))


def _round_t(t: float) -> float:
    return round(t, 6)


def generate_trace(spec: ScenarioSpec, path: str,
                   progress: Callable[[int], None] | None = None
                   ) -> dict[str, Any]:
    """Write the trace for ``spec`` to ``path`` (gzip when the path
    ends ``.gz``); returns generation stats.  Streaming: memory is
    bounded by the concurrently-alive pod set (their scheduled
    departures sit in a heap), never by total pods."""
    rng = np.random.default_rng(spec.seed)
    n = spec.cluster.num_nodes
    racks_of: dict[tuple[int, int], list[str]] = {}
    for i in range(n):
        zone = i % spec.cluster.zones
        rack = (i // spec.cluster.zones) % spec.cluster.racks_per_zone
        racks_of.setdefault((zone, rack), []).append(f"node-{i:04d}")
    rack_keys = sorted(racks_of)

    # Scheduled future events: (t, seq, json-line).  seq breaks ties
    # deterministically in emission order.
    heap: list[tuple[float, int, str]] = []
    seq = 0

    def _push(t: float, obj: dict[str, Any]) -> None:
        nonlocal seq
        heapq.heappush(heap, (t, seq, _dump(obj)))
        seq += 1

    stats = {"pods": 0, "events": 0, "gangs": 0, "deletes": 0,
             "link_bursts": 0, "node_churn": 0, "state_faults": 0,
             "zone_outages": 0, "node_upgrades": 0,
             "shaped_gangs": 0}
    # Recent alive pods per service, for peer edges (bounded; peers
    # may outlive their partners — the join skips unresolved peers).
    recent: dict[int, list[str]] = {}
    down_until: dict[str, float] = {}
    pod_seq = 0
    gang_seq = 0

    class_probs = np.array([spec.batch_fraction, spec.gang_fraction,
                            spec.longrun_fraction])
    if class_probs.sum() > 1.0:
        raise ValueError("pod-class fractions sum past 1.0")

    def _requests() -> dict[str, float]:
        return {
            "cpu": round(float(rng.uniform(*spec.cpu_range)), 4),
            "mem": round(float(rng.uniform(*spec.mem_range)), 4),
            "net_bw": round(float(rng.uniform(*spec.netbw_range)), 4),
        }

    def _peers(svc: int) -> dict[str, float]:
        earlier = recent.get(svc, [])
        if not earlier or rng.random() >= spec.peer_fraction:
            return {}
        count = int(rng.integers(1, spec.max_peers + 1))
        chosen = rng.choice(len(earlier),
                            size=min(count, len(earlier)),
                            replace=False)
        return {earlier[int(c)]: round(float(rng.uniform(0.5, 20.0)), 3)
                for c in chosen}

    def _note_recent(svc: int, name: str) -> None:
        lst = recent.setdefault(svc, [])
        lst.append(name)
        if len(lst) > 32:
            del lst[:len(lst) - 32]

    def _lifetime(mean: float) -> float:
        return spec.lifetime_floor_s + float(rng.exponential(mean))

    with _open_write(path) as fh:
        header = {
            "kind": "header",
            "format": TRACE_FORMAT,
            "version": TRACE_VERSION,
            "seed": spec.seed,
            "spec": spec_to_json(spec),
        }
        fh.write(_dump(header) + "\n")

        def _emit(obj: dict[str, Any]) -> None:
            fh.write(_dump(obj) + "\n")
            stats["events"] += 1
            if progress is not None and stats["events"] % 65536 == 0:
                progress(stats["events"])

        def _drain_heap(upto: float) -> None:
            while heap and heap[0][0] <= upto:
                _, _, line = heapq.heappop(heap)
                fh.write(line + "\n")
                stats["events"] += 1

        zone_outage_fired = False
        upgrade_fired = False
        t = 0.0
        while t < spec.duration_s:
            _drain_heap(t)
            tv = _round_t(t)
            # --- scheduled mass events (v2, deterministic) ---------
            if (spec.zone_outage_at_s >= 0.0 and not zone_outage_fired
                    and t >= spec.zone_outage_at_s):
                zone_outage_fired = True
                z = spec.zone_outage_zone % max(1, spec.cluster.zones)
                znodes = sorted(
                    nm for (zz, _r), nms in racks_of.items()
                    if zz == z for nm in nms)
                up_t = _round_t(t + spec.zone_outage_duration_s)
                _emit({"t": tv, "kind": "zone_down", "zone": z,
                       "nodes": znodes})
                _push(up_t, {"t": up_t, "kind": "zone_up", "zone": z,
                             "nodes": znodes})
                for nm in znodes:
                    down_until[nm] = up_t
                stats["zone_outages"] += 1
            if (spec.rolling_upgrade_at_s >= 0.0 and not upgrade_fired
                    and t >= spec.rolling_upgrade_at_s):
                upgrade_fired = True
                bsz = max(1, int(spec.rolling_upgrade_batch))
                hold = max(spec.tick_s, spec.rolling_upgrade_hold_s)
                for b, start in enumerate(range(0, n, bsz)):
                    bt = _round_t(t + b * hold)
                    up_t = _round_t(t + (b + 1) * hold)
                    for i in range(start, min(start + bsz, n)):
                        name = f"node-{i:04d}"
                        obj = {"t": bt, "kind": "node_upgrade",
                               "node": name}
                        if bt <= tv:
                            _emit(obj)
                        else:
                            _push(bt, obj)
                        _push(up_t, {"t": up_t, "kind": "node_up",
                                     "node": name})
                        stats["node_upgrades"] += 1
            # --- fault/churn processes (Poisson per tick) ----------
            if spec.link_burst_rate_per_s > 0.0:
                for _ in range(int(rng.poisson(
                        spec.link_burst_rate_per_s * spec.tick_s))):
                    rk = rack_keys[int(rng.integers(len(rack_keys)))]
                    nodes = racks_of[rk]
                    factor = round(float(spec.link_burst_factor
                                         * rng.uniform(0.5, 1.5)), 3)
                    _emit({"t": tv, "kind": "link_degrade",
                           "nodes": nodes, "factor": factor})
                    _push(_round_t(t + spec.link_burst_duration_s),
                          {"t": _round_t(
                              t + spec.link_burst_duration_s),
                           "kind": "link_repair",
                           "nodes": nodes, "factor": factor})
                    stats["link_bursts"] += 1
            if spec.node_churn_rate_per_s > 0.0:
                for nm in [nm for nm, up in down_until.items()
                           if up <= t]:
                    del down_until[nm]
                for _ in range(int(rng.poisson(
                        spec.node_churn_rate_per_s * spec.tick_s))):
                    name = f"node-{int(rng.integers(n)):04d}"
                    if name in down_until:
                        continue
                    up_t = _round_t(t + spec.node_down_duration_s)
                    down_until[name] = up_t
                    _emit({"t": tv, "kind": "node_down", "node": name})
                    _push(up_t, {"t": up_t, "kind": "node_up",
                                 "node": name})
                    stats["node_churn"] += 1
            if spec.state_fault_rate_per_s > 0.0:
                for _ in range(int(rng.poisson(
                        spec.state_fault_rate_per_s * spec.tick_s))):
                    fault = STATE_FAULT_CLASSES[
                        int(rng.integers(len(STATE_FAULT_CLASSES)))]
                    _emit({"t": tv, "kind": "state_fault",
                           "fault": fault})
                    stats["state_faults"] += 1

            # --- arrivals (diurnal Poisson) ------------------------
            arrivals = int(rng.poisson(
                spec.base_rate * spec.tick_s * _diurnal(spec, t)))
            made = 0
            while made < arrivals:
                roll = rng.random()
                if roll < class_probs[0]:
                    cls, mean = "batch", spec.batch_lifetime_s
                elif roll < class_probs[0] + class_probs[1]:
                    cls, mean = "gang", spec.gang_lifetime_s
                elif roll < class_probs.sum():
                    cls, mean = "longrun", None
                else:
                    cls, mean = "serving", spec.serving_lifetime_s
                if cls == "gang":
                    size = int(spec.gang_sizes[
                        int(rng.integers(len(spec.gang_sizes)))])
                    group = f"gang-{gang_seq:06d}"
                    gang_seq += 1
                    # Elastic shape family (v2): declared on every
                    # member, identical string.  The 0-fraction guard
                    # short-circuits the rng draw, so v1-equivalent
                    # specs keep a byte-identical event stream.
                    shapes = ""
                    if (spec.gang_shapes_fraction > 0.0
                            and size >= 2
                            and rng.random()
                            < spec.gang_shapes_fraction):
                        shapes = f"{size},{max(1, size // 2)}:0.5"
                        stats["shaped_gangs"] += 1
                    life = _round_t(t + _lifetime(mean))
                    names = []
                    for m in range(size):
                        name = f"{group}-w{m:03d}"
                        names.append(name)
                        pod = {
                            "name": name,
                            "cpu": spec.gang_cpu,
                            "mem": spec.gang_mem,
                            "net_bw": spec.gang_netbw,
                            "pod_group": group,
                            "gang_min_member": size,
                            "priority": 5.0,
                        }
                        if shapes:
                            pod["gang_shapes"] = shapes
                        _emit({"t": tv, "kind": "pod",
                               "pod_class": cls, "pod": pod})
                        pod_seq += 1
                    for name in names:
                        _push(life, {"t": life, "kind": "delete",
                                     "pod": name})
                        stats["deletes"] += 1
                    stats["pods"] += size
                    stats["gangs"] += 1
                    made += size
                    continue
                svc = int(rng.integers(spec.services))
                name = f"pod-{cls[0]}{svc:03d}-{pod_seq:08d}"
                pod_seq += 1
                pod = {"name": name, "group": f"svc-{svc % 28}",
                       "priority": round(float(rng.uniform(0, 10)), 3),
                       **_requests()}
                peers = _peers(svc)
                if peers:
                    pod["peers"] = peers
                _emit({"t": tv, "kind": "pod", "pod_class": cls,
                       "pod": pod})
                _note_recent(svc, name)
                if mean is not None:
                    life = _round_t(t + _lifetime(mean))
                    _push(life, {"t": life, "kind": "delete",
                                 "pod": name})
                    stats["deletes"] += 1
                stats["pods"] += 1
                made += 1
            t += spec.tick_s

        # Flush every remaining scheduled event (departures/repairs
        # past the arrival horizon) so the trace closes consistent.
        _drain_heap(math.inf)

    return stats
