"""Load/soak the NATIVE extender shim at >=128 concurrent connections
(VERDICT r4 weak #8 / next-round #9).

``native/extender.cpp`` is thread-per-connection; functional tests
drive it over a handful of sockets, and ``bench/extender_qps.py``
benches the PYTHON HTTP front.  This harness drives the real binary:

- ``conc_clients`` (default 128, the batcher's tuning concurrency)
  persistent keep-alive HTTP clients POSTing /prioritize through the
  shim -> UDS -> Python batcher -> kernel path;
- thread/fd counts of the shim process sampled from /proc at peak,
  so "no fd/thread exhaustion" is a recorded observation;
- a backend KILL under full load: every in-flight and subsequent
  /prioritize must fail OPEN (HTTP 200, neutral ``[]`` — the stock
  scheduler then decides alone), and /healthz must still answer.

Run: ``python -m kubernetesnetawarescheduler_tpu.bench.native_load
[--write]`` -> ``bench_artifacts/native_extender_load.json``.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import subprocess
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _proc_stats(pid: int) -> dict:
    out: dict = {}
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("Threads:"):
                    out["threads"] = int(line.split()[1])
    except OSError:
        pass
    try:
        out["fds"] = len(os.listdir(f"/proc/{pid}/fd"))
    except OSError:
        pass
    return out


def _args_payload(i: int) -> bytes:
    """The same ExtenderArgs shape extender_qps drives in-process —
    one payload builder, serialized here for the wire."""
    from kubernetesnetawarescheduler_tpu.bench.extender_qps import (
        _prioritize_args,
    )

    return json.dumps(_prioritize_args(i)).encode()


class _Client:
    """One persistent keep-alive connection; counts outcomes."""

    def __init__(self, port: int, n_requests: int, idx: int):
        self.port = port
        self.n = n_requests
        self.idx = idx
        self.ok = 0
        self.neutral = 0  # 200 with [] body (fail-open)
        self.errors = 0

    def _connect(self) -> http.client.HTTPConnection:
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=60)
        # TCP_NODELAY, as kube-scheduler's Go HTTP client sets it:
        # http.client writes headers and body as separate sends, and
        # without this each POST stalls ~40 ms on the Nagle /
        # delayed-ACK interaction — the load test would measure the
        # stall, not the shim.
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP,
                             socket.TCP_NODELAY, 1)
        return conn

    def run(self) -> None:
        conn = self._connect()
        for i in range(self.n):
            try:
                conn.request(
                    "POST", "/prioritize",
                    body=_args_payload(self.idx * 100000 + i),
                    headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    self.errors += 1
                    continue
                doc = json.loads(body)
                if doc == []:
                    self.neutral += 1
                else:
                    self.ok += 1
            except Exception:  # noqa: BLE001 — counted, not raised
                self.errors += 1
                try:
                    conn.close()
                    conn = self._connect()
                except OSError:
                    return
        conn.close()


def _backend_kill_under_load(conc_clients: int,
                             requests_per_client: int) -> dict:
    """SIGKILL a real backend PROCESS with the full client fleet
    live.  An in-process ScorerServer.stop() cannot model this since
    round 5's pooled backend connections: stop() only closes the
    ACCEPT loop while live handler threads keep serving the pooled
    sockets, so nothing ever failed.  A separate serve.py process
    (--cluster fake:N, --uds) dies for real — the kernel closes every
    pooled socket, the shim's reconnect finds no listener, and every
    response from that instant must fail OPEN (200-neutral for
    /prioritize), with /healthz still live and the thread fleet
    drained.  N is small: kill semantics are N-independent and the
    subprocess pays its own XLA compiles."""
    import sys
    import tempfile

    uds = os.path.join(tempfile.mkdtemp(), "kill.sock")
    backend = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         "from kubernetesnetawarescheduler_tpu import serve; "
         f"serve.main(['--cluster', 'fake:128', '--uds', {uds!r}])"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    port = _free_port()
    shim = subprocess.Popen(
        [os.path.join(_REPO, "native", "netaware_extender"),
         str(port), uds],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            if not os.path.exists(uds):
                time.sleep(0.1)
                continue
            try:
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=0.5)
                c.request("GET", "/healthz")
                if c.getresponse().status == 200:
                    c.close()
                    break
                c.close()
            except OSError:
                time.sleep(0.1)
        else:
            raise SystemExit("kill-phase shim/backend did not come up")
        # Warm the backend's compile shapes so the kill lands during
        # steady serving, not during the first compile.
        warm = _Client(port, 2, 4000)
        warm.run()
        if warm.ok == 0:
            raise SystemExit("kill-phase backend never scored")

        clients = [_Client(port, requests_per_client, 1000 + i)
                   for i in range(conc_clients)]
        threads = [threading.Thread(target=c.run) for c in clients]
        total = conc_clients * requests_per_client
        for t in threads:
            t.start()
        # Kill once the run is observably MID-flight (some responses
        # in, most still outstanding) — a fixed sleep either misses a
        # fast fleet entirely or lands inside warmup of a slow one.
        deadline = time.time() + 30
        while time.time() < deadline:
            done_now = sum(cl.ok + cl.neutral for cl in clients)
            if done_now >= max(1, total // 10):
                break
            time.sleep(0.005)
        backend.kill()  # SIGKILL mid-flight: sockets die with it
        for t in threads:
            t.join()
        neutral = sum(c.neutral for c in clients)
        errors2 = sum(c.errors for c in clients)
        # Settle-poll: the C++ per-connection threads exit on client
        # EOF, which lags the Python-side join; one instant sample
        # would read teardown-in-progress as a leak.
        after = _proc_stats(shim.pid)
        settle = time.time() + 5
        while after.get("threads", 0) > 4 and time.time() < settle:
            time.sleep(0.05)
            after = _proc_stats(shim.pid)
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        c.request("GET", "/healthz")
        healthz = c.getresponse().status
        c.close()
        return {
            "neutral_responses": neutral,
            # Scored before the SIGKILL landed.
            "scored_responses": sum(cl.ok for cl in clients),
            "errors": errors2,
            "requests": conc_clients * requests_per_client,
            "healthz_after": healthz,
            "shim_after": after,
            "fail_open": (errors2 == 0 and healthz == 200
                          and neutral > 0),
        }
    finally:
        for proc in (shim, backend):
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass


def run_native_load(num_nodes: int = 5120, max_pods: int = 256,
                    conc_clients: int = 128,
                    requests_per_client: int = 16,
                    kill_backend_midway: bool = True,
                    seed: int = 0) -> dict:
    import numpy as np

    from kubernetesnetawarescheduler_tpu.api.extender import (
        ExtenderHandlers,
    )
    from kubernetesnetawarescheduler_tpu.api.server import ScorerServer
    from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
        ClusterSpec,
        build_fake_cluster,
        feed_metrics,
    )
    from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
    from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
    from kubernetesnetawarescheduler_tpu.core.state import round_up

    import tempfile

    subprocess.run(["make", "-C", os.path.join(_REPO, "native")],
                   check=True, capture_output=True)

    cfg = SchedulerConfig(max_nodes=round_up(num_nodes, 128),
                          max_pods=max_pods, max_peers=4)
    cluster, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed))
    loop = SchedulerLoop(cluster, cfg, method="parallel")
    loop.encoder.set_network(lat, bw)
    feed_metrics(cluster, loop.encoder, np.random.default_rng(seed + 1))
    handlers = ExtenderHandlers(loop)
    uds = os.path.join(tempfile.mkdtemp(), "scorer.sock")
    server = ScorerServer(handlers, uds)
    server.start()

    port = _free_port()
    shim = subprocess.Popen(
        [os.path.join(_REPO, "native", "netaware_extender"),
         str(port), uds],
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                c = http.client.HTTPConnection("127.0.0.1", port,
                                               timeout=0.5)
                c.request("GET", "/healthz")
                if c.getresponse().status == 200:
                    c.close()
                    break
                c.close()
            except OSError:
                time.sleep(0.05)
        else:
            raise SystemExit("shim did not come up")

        # Warm with the FULL concurrent fleet, twice (extender_qps'
        # pattern): demand-sized waves quantize the pod pad, so only
        # fleet-sized waves compile the shapes the timed window will
        # hit — a trickle warmup left a ~1 s XLA compile inside the
        # measured wall (observed as a phantom 10x qps regression).
        for _ in range(2):
            wthreads = [
                threading.Thread(
                    target=_Client(port, requests_per_client,
                                   5000 + i).run)
                for i in range(conc_clients)]
            for t in wthreads:
                t.start()
            for t in wthreads:
                t.join()

        clients = [_Client(port, requests_per_client, i)
                   for i in range(conc_clients)]
        threads = [threading.Thread(target=c.run) for c in clients]
        # Max-sampling poller: a single instant sample can miss the
        # fleet entirely when the warmed run completes in fractions
        # of a second.
        peak: dict = {}
        stop_sampling = threading.Event()

        def _sample_peak() -> None:
            while not stop_sampling.wait(0.02):
                s = _proc_stats(shim.pid)
                for k, v in s.items():
                    peak[k] = max(peak.get(k, 0), v)

        sampler = threading.Thread(target=_sample_peak, daemon=True)
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        sampler.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stop_sampling.set()
        sampler.join(timeout=2)
        total = sum(c.ok + c.neutral for c in clients)
        scored = sum(c.ok for c in clients)
        errors = sum(c.errors for c in clients)
        qps = total / wall if wall > 0 else 0.0

        result = {
            "num_nodes": num_nodes,
            "conc_clients": conc_clients,
            "requests": conc_clients * requests_per_client,
            "scored_responses": scored,
            "errors": errors,
            # One timed pass here, so best == mean; both keys are
            # emitted to keep the schema aligned with extender_qps
            # (whose headline is best-of-N, named as such).
            "conc_qps_best": round(qps, 1),
            "conc_qps_mean": round(qps, 1),
            "wall_s": round(wall, 2),
            "shim_peak": peak,
        }

        if kill_backend_midway:
            result["backend_kill"] = _backend_kill_under_load(
                conc_clients, requests_per_client)
        return result
    finally:
        try:
            # Idempotent if the kill branch already stopped it; a
            # throughput-only sweep (kill_backend_midway=False) must
            # not leak a live server thread pool per call.
            server.stop()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        shim.terminate()
        try:
            shim.wait(timeout=5)
        except subprocess.TimeoutExpired:
            shim.kill()


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--write", nargs="?", const=os.path.join(
        _REPO, "bench_artifacts", "native_extender_load.json"))
    ap.add_argument("--clients", type=int, default=128)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--nodes", type=int, default=5120)
    args = ap.parse_args(argv)

    import jax

    # This artifact is the CPU reference (the chip's serving numbers
    # come from tools/tpu_legs.py serving_qps).  Forcing CPU also
    # keeps the CLI usable while the axon tunnel is wedged — the
    # sitecustomize otherwise routes backend init at the TPU and
    # hangs PJRT init indefinitely.
    jax.config.update("jax_platforms", "cpu")

    doc = run_native_load(num_nodes=args.nodes,
                          conc_clients=args.clients,
                          requests_per_client=args.requests)
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env

    doc["backend"] = jax.default_backend()
    doc["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    doc["bench_env"] = bench_env()
    if doc["bench_env"].get("git_sha"):
        doc["git"] = doc["bench_env"]["git_sha"]  # legacy key
    print(json.dumps(doc))
    if args.write:
        with open(args.write, "w") as f:
            json.dump(doc, f, indent=1)


if __name__ == "__main__":
    main()
