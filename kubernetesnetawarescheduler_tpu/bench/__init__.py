"""Benchmark & evaluation harness.

The reference's evaluation was entirely offline and external: a manual
N-pods x 100 MB transfer workload (datasets/customNetworkBenchmark) and
clusterloader2 runs (datasets/clusterloader2), with only the result
artifacts committed.  This package recreates that harness *as code*:
fake-cluster generation, workload replay for the five BASELINE.json
configs, and emitters for the same artifact shapes.
"""

from kubernetesnetawarescheduler_tpu.bench.fakecluster import (  # noqa: F401
    ClusterSpec,
    WorkloadSpec,
    build_fake_cluster,
    generate_workload,
)
