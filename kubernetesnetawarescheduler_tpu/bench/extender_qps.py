"""Extender webhook QPS bench: the micro-batched Score/Filter path.

The reference scheduler's cycle was per-pod synchronous — 5 serial
node_exporter scrapes per scheduled pod (scheduler.go:191, :275-279).
Round 1 of this build reproduced that defect in miniature at the
webhook boundary: every ``/prioritize`` encoded one pod into a full
``max_pods``-shaped batch and dispatched a ``max_pods x N`` kernel.
This bench quantifies the fix (api/extender._ScoreBatcher):

- ``seq_qps``          one-at-a-time requests through the batcher
                       (demand-sized 8-pod kernels);
- ``seq_maxpods_qps``  the round-1 shape, for comparison: one pod in a
                       ``max_pods``-padded batch per dispatch;
- ``conc_qps_best``    many client threads — natural batching
                       coalesces them into shared dispatches; best of
                       the timed passes (``conc_qps_mean`` is the
                       mean, ``conc_qps_passes`` the raw list).
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from kubernetesnetawarescheduler_tpu.api.extender import ExtenderHandlers
from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
    ClusterSpec,
    build_fake_cluster,
    feed_metrics,
)
from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
from kubernetesnetawarescheduler_tpu.core.pallas_score import score_pods_auto
from kubernetesnetawarescheduler_tpu.core.state import round_up


@dataclasses.dataclass
class QpsResult:
    num_nodes: int
    max_pods: int
    seq_qps: float
    seq_maxpods_qps: float
    # Best and mean over the timed passes, NAMED as such (ADVICE r5
    # #1: max-of-N reported as the headline number overstates the
    # sustained rate; the mean is the honest steady-state figure, the
    # best shows what a quiet machine reaches).
    conc_qps_best: float
    conc_clients: int
    mean_batch: float  # pods per kernel dispatch under concurrency
    conc_qps_mean: float = 0.0
    conc_dispatches: int = 0  # kernel dispatches in the timed window
    batch_occupancy: float = 0.0  # mean_batch / max_pods
    # Every timed pass, so the best-of selection behind
    # ``conc_qps_best`` is visible in the artifact itself, not just in
    # the docs (advisor r4: a best-of-N number with the N hidden
    # systematically overstates the steady state).
    conc_qps_passes: list[float] = dataclasses.field(
        default_factory=list)
    # Second concurrency point + transport budget (VERDICT r4 #3):
    # conc_qps at 128 clients is STRUCTURALLY capped by
    # clients / dispatch_rtt (each client has one request in flight;
    # a coalesced dispatch serves at most `clients` of them per RTT).
    # On the tunneled dev chip (~65 ms RTT) that ceiling is ~2,000 —
    # the gap to 5k conc_qps is transport concurrency, not kernel
    # throughput.  conc512_qps measures the same path with 4x the
    # in-flight budget; rtt_budget records the model's terms so the
    # artifact carries the non-transport residue on its face.
    conc512_qps: float = 0.0
    conc512_clients: int = 0
    rtt_budget: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _prioritize_args(i: int) -> dict:
    return {
        "pod": {
            "metadata": {"name": f"qps-pod-{i}", "uid": f"qps-{i}"},
            "spec": {
                "schedulerName": "netAwareScheduler",
                "containers": [{"resources": {"requests": {
                    "cpu": "500m", "memory": "1Gi"}}}],
            },
        },
        "nodenames": [f"node-{j:04d}" for j in range(0, 64)],
    }


def run_qps(num_nodes: int = 5120, max_pods: int = 256,
            seq_requests: int = 32, conc_clients: int = 128,
            conc_requests: int = 2048, seed: int = 0) -> QpsResult:
    cfg = SchedulerConfig(max_nodes=round_up(num_nodes, 128),
                          max_pods=max_pods, max_peers=4)
    cluster, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed))
    loop = SchedulerLoop(cluster, cfg, method="parallel")
    loop.encoder.set_network(lat, bw)
    feed_metrics(cluster, loop.encoder, np.random.default_rng(seed + 1))
    handlers = ExtenderHandlers(loop)

    # Warm both compile shapes outside the timed windows.
    handlers.prioritize(_prioritize_args(0))
    enc = loop.encoder.encode_pods([_pod_for_maxpods()],
                                   node_of=loop._peer_node, lenient=True)
    np.asarray(score_pods_auto(loop.encoder.snapshot(), enc, cfg))

    start = time.perf_counter()
    for i in range(seq_requests):
        handlers.prioritize(_prioritize_args(i))
    seq_qps = seq_requests / (time.perf_counter() - start)

    # Round-1 shape: a max_pods-padded batch per request.
    start = time.perf_counter()
    for i in range(seq_requests):
        b = loop.encoder.encode_pods([_pod_for_maxpods()],
                                     node_of=loop._peer_node, lenient=True)
        np.asarray(score_pods_auto(loop.encoder.snapshot(), b, cfg))
    seq_maxpods_qps = seq_requests / (time.perf_counter() - start)

    # Concurrency: natural batching across client threads.  Two
    # passes — the first warms the demand-sized coalesced batch
    # shapes (each distinct quantized batch size is its own XLA
    # compile; timing the first concurrent burst measured compilation,
    # observed as a phantom 2-3x "regression" between identical runs).
    done = []
    lock = threading.Lock()

    def client(base: int, per_client: int) -> None:
        for i in range(per_client):
            handlers.prioritize(_prioritize_args(base * 1000 + i))
            with lock:
                done.append(1)

    def run_threads(n_clients: int = conc_clients,
                    per_client: int | None = None) -> float:
        per = (per_client if per_client is not None
               else conc_requests // conc_clients)
        threads = [threading.Thread(target=client, args=(c, per))
                   for c in range(n_clients)]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - start

    # Warmup TWICE: wave sizes vary run to run, so one pass does not
    # cover the pow2 (pod-pad x candidate-pad) shape universe — a
    # fresh XLA compile in the timed window reads as a phantom 2-20x
    # regression (gather compile alone is ~6 s through the tunnel).
    run_threads()
    run_threads()
    # Two timed passes: the artifact reports BOTH the best (compile
    # luck excluded, quiet-machine figure) and the mean (sustained
    # rate), plus every pass raw.
    conc_qps = 0.0
    dispatches = 0
    mean_batch = 0.0
    best_wall = 0.0
    passes: list[float] = []
    for _ in range(2):
        done.clear()
        dispatches_before = _dispatch_count(handlers)
        conc_wall = run_threads()
        qps = len(done) / conc_wall
        passes.append(round(qps, 1))
        if qps > conc_qps:
            conc_qps = qps
            dispatches = _dispatch_count(handlers) - dispatches_before
            mean_batch = len(done) / dispatches if dispatches else 0.0
            best_wall = conc_wall
    # 4x the in-flight budget: with one request per client thread, a
    # coalescing batcher's throughput ceiling is clients/dispatch_rtt
    # regardless of kernel speed; 512 clients raise the ceiling to
    # where the kernel (not transport concurrency) is the limit.
    conc2 = 4 * conc_clients
    per2 = max(4, conc_requests // conc2 * 2)
    run_threads(conc2, per2)  # warm the larger coalesced shapes
    done.clear()
    d_before = _dispatch_count(handlers)
    wall2 = run_threads(conc2, per2)
    qps2 = len(done) / wall2
    d2 = _dispatch_count(handlers) - d_before
    rtt_est_ms = wall2 / d2 * 1e3 if d2 else 0.0
    # Each concurrency's ceiling uses ITS OWN measured dispatch
    # interval (coalesced batch size grows with clients, so the
    # 512-client interval would understate the 128-client ceiling).
    rtt128_ms = best_wall / dispatches * 1e3 if dispatches else 0.0
    return QpsResult(
        num_nodes=num_nodes, max_pods=max_pods,
        seq_qps=round(seq_qps, 1),
        seq_maxpods_qps=round(seq_maxpods_qps, 1),
        conc_qps_best=round(conc_qps, 1),
        conc_clients=conc_clients,
        mean_batch=round(mean_batch, 2),
        conc_qps_mean=round(float(np.mean(passes)), 1) if passes else 0.0,
        conc_dispatches=dispatches,
        batch_occupancy=round(mean_batch / max_pods, 3),
        conc_qps_passes=passes,
        conc512_qps=round(qps2, 1),
        conc512_clients=conc2,
        rtt_budget={
            "dispatch_interval_ms_conc": round(rtt128_ms, 2),
            "dispatch_interval_ms_conc512": round(rtt_est_ms, 2),
            "dispatches_conc512": d2,
            # In-flight ceiling at each concurrency (one request per
            # client bounds what one dispatch interval can serve),
            # each from ITS OWN interval.  measured/ceiling ~ 1 means
            # the gap to any higher target is transport concurrency,
            # not the kernel.
            "ceiling_conc_qps": round(
                conc_clients / (rtt128_ms / 1e3), 1)
            if rtt128_ms else 0.0,
            "ceiling_conc512_qps": round(
                conc2 / (rtt_est_ms / 1e3), 1) if rtt_est_ms else 0.0,
        },
    )


def _pod_for_maxpods():
    from kubernetesnetawarescheduler_tpu.k8s.types import Pod
    return Pod(name="qps-ref", requests={"cpu": 0.5, "mem": 1.0})


def _dispatch_count(handlers: ExtenderHandlers) -> int:
    return handlers._batcher.dispatches


def main(argv=None) -> None:
    """``--write [PATH]`` persists the result (with the executing
    backend recorded) as the bench artifact —
    ``bench_artifacts/extender_qps.json`` by default — so the number
    the docs cite is regenerable by one command."""
    import argparse
    import json
    import os

    import jax

    ap = argparse.ArgumentParser()
    ap.add_argument("--write", nargs="?", const="", default=None,
                    help="persist to PATH (default: the repo's "
                         "bench_artifacts/extender_qps.json)")
    ap.add_argument("--tpu", action="store_true",
                    help="do NOT force the CPU backend (hardware "
                         "runs go through tools/tpu_legs.py "
                         "serving_qps, which also asserts the "
                         "backend; without a live chip the axon "
                         "sitecustomize hangs PJRT init forever)")
    args = ap.parse_args(argv)
    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env

    doc = run_qps().to_dict()
    doc["backend"] = jax.default_backend()
    doc["bench_env"] = bench_env()
    if doc["bench_env"].get("git_sha"):
        doc["git"] = doc["bench_env"]["git_sha"]  # legacy key
    print(json.dumps(doc))
    if args.write is not None:
        path = args.write or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            "bench_artifacts", "extender_qps.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)


if __name__ == "__main__":
    main()
