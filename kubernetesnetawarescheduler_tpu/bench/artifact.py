"""Shared artifact writer for every bench-suite leg.

Each suite leg used to repeat the same three steps by hand: stamp the
provenance block (``detail.bench_env``), join the output path, and
``json.dump`` the doc — with the quality leg briefly shipping an
artifact whose env block was stamped before the run finished.  This
helper is the single place that contract lives: stamp-at-write, one
dump shape (indent=2, UTF-8), and the path appended to the caller's
artifact list in the same call.
"""

from __future__ import annotations

import json
import os

from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env


def stamp_provenance(doc: dict) -> dict:
    """Ensure ``doc.detail.bench_env`` is present and non-empty (the
    bench_check Rule 1 contract).  A leg that already stamped a fresher
    env block keeps it."""
    detail = doc.setdefault("detail", {})
    if not detail.get("bench_env"):
        detail["bench_env"] = bench_env()
    return doc


def write_artifact(out_dir: str | None, filename: str, doc: dict,
                   artifacts: list[str] | None = None) -> str | None:
    """Stamp provenance and persist ``doc`` as
    ``<out_dir>/<filename>``; returns the path (None when ``out_dir``
    is None — smoke callers that want the doc but no file).  When
    ``artifacts`` is given the path is appended to it, matching the
    ``SuiteResult.artifacts`` convention."""
    stamp_provenance(doc)
    if out_dir is None:
        return None
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, filename)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2)
    if artifacts is not None:
        artifacts.append(path)
    return path
