"""Shared bench-environment fingerprint for every emitted artifact.

Every benchmark JSON this repo writes carries a ``bench_env`` block so
a number can be traced to the machine and tree that produced it.  The
earlier shape read env vars the harness never set (``BENCH_HOST`` et
al.), leaving ``{}`` in every artifact — this computes the facts
directly and falls back to empty strings only where the platform
genuinely cannot answer.
"""

from __future__ import annotations

import os
import socket
import subprocess


def git_sha(repo_dir: str | None = None) -> str:
    """Short commit sha of the tree that produced the run ('' outside
    a checkout or without git)."""
    if repo_dir is None:
        repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo_dir,
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.SubprocessError):
        return ""


def accel_platform() -> str:
    """Accelerator backend the producing process executed on ('cpu',
    'tpu', 'gpu'; '' when jax is absent).  Only consults an
    already-imported jax — bench processes have it loaded long before
    they stamp artifacts, and jax-free tools (artifact linters) must
    not pay a jax import to read a hostname."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return ""
    try:
        return str(jax.default_backend())
    except Exception:
        return ""


def bench_env() -> dict:
    """{host, cpu_count, loadavg_1m, platform, git_sha} — the
    provenance block every bench artifact embeds as ``bench_env``."""
    try:
        load1 = round(os.getloadavg()[0], 2)
    except OSError:  # platforms without getloadavg
        load1 = -1.0
    return {
        "host": socket.gethostname(),
        "cpu_count": os.cpu_count() or 0,
        "loadavg_1m": load1,
        "platform": accel_platform(),
        "git_sha": git_sha(),
    }
