"""The five BASELINE.json benchmark configs (+ soft-affinity audit),
runnable as one suite.

The reference published its evaluation as committed artifacts only —
``datasets/customNetworkBenchmark/*.data`` (5-line timing files,
5podsCustomScheduler.data:1-5) and clusterloader2
``ResourceUsageSummary_load_*.json`` (percentile -> [{Name, Cpu, Mem}]
maps) — produced by hand on a live 5-node cluster (SURVEY.md §3.5).
This module recreates that harness **as code** against the fake cluster,
one function per BASELINE.json config:

1. ``density``  — 100-node clusterloader2 density replay, netperf
                  latency-only Score; emits a ResourceUsageSummary-style
                  JSON of the scheduler's own cpu/mem percentiles
                  (sampled live, the way clusterloader2 sampled system
                  containers).
2. ``custom_network`` — the customNetworkBenchmark replay at 1k nodes:
                  N client pods each pushing ``dataPerPod`` MB to placed
                  server pods; completion simulated on the ground-truth
                  bandwidth/latency matrices; emits the exact ``.data``
                  schema for our scheduler vs a network-oblivious
                  spreading baseline (the "Original Scheduler" role).
3. ``affinity`` — inter-pod affinity/anti-affinity as batched constraint
                  masks; validates ZERO violations host-side.
4. ``binpack``  — multi-resource bin-packing (cpu/mem/net-bw caps) with
                  soft balance penalties; validates zero overcommit and
                  reports utilization imbalance with the penalty on vs
                  off.
5. ``sidecar``  — service-mesh sidecar co-placement over an Istio-style
                  service topology graph at 5k nodes; reports the
                  sidecar→app co-location rate.

Every config returns a :class:`SuiteResult` and (optionally) writes its
artifacts under ``out_dir`` in the reference's own dataset shapes, so
the comparison with §6 of SURVEY.md is apples-to-apples.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.bench.artifact import write_artifact
from kubernetesnetawarescheduler_tpu.bench.density import run_density
from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
    ClusterSpec,
    NodeClassSpec,
    WorkloadSpec,
    build_fake_cluster,
    feed_metrics,
    generate_workload,
)
from kubernetesnetawarescheduler_tpu.config import (
    SchedulerConfig,
    ScoreWeights,
)
from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
from kubernetesnetawarescheduler_tpu.k8s.types import Pod


@dataclasses.dataclass
class SuiteResult:
    config: str
    metrics: dict
    artifacts: list[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# Artifact emitters — the reference's dataset schemas.
# ---------------------------------------------------------------------------


def write_data_file(path: str, pods_scheduled: int, data_per_pod_mb: float,
                    affected_nodes: Sequence[str], time_ms: float) -> None:
    """The customNetworkBenchmark ``.data`` schema — 5 lines:
    podsScheduled / dataPerPod(MB) / affectedNodes / separator / time(ms)
    (5podsCustomScheduler.data:1-5)."""
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(f"podsScheduled: {pods_scheduled}\n")
        fh.write(f"dataPerPod(MB): {data_per_pod_mb:g}\n")
        fh.write(f"affectedNodes: {', '.join(affected_nodes)}\n")
        fh.write("---------------------\n")
        fh.write(f"time(ms): {time_ms:.0f}\n")


def write_resource_usage_summary(path: str,
                                 samples_cpu: Sequence[float],
                                 samples_mem: Sequence[float],
                                 name: str = "netaware-scheduler/scorer"
                                 ) -> None:
    """clusterloader2 ``ResourceUsageSummary`` schema: a map of
    percentile-string -> [{Name, Cpu (cores), Mem (bytes)}]
    (ResourceUsageSummary_load_Custom_Scheduler.json:1-9)."""
    cpu = np.asarray(samples_cpu, np.float64)
    mem = np.asarray(samples_mem, np.float64)
    if cpu.size == 0:
        cpu = np.zeros(1)
        mem = np.zeros(1)
    out = {}
    for pct in ("50", "90", "99", "100"):
        out[pct] = [{
            "Name": name,
            "Cpu": float(np.percentile(cpu, int(pct))),
            "Mem": int(np.percentile(mem, int(pct))),
        }]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")


class UsageSampler(threading.Thread):
    """Samples this process's cpu (cores) and RSS (bytes) on a fixed
    period — our stand-in for clusterloader2's system-container
    resource sampling (the reference committed its output as
    ResourceUsageSummary JSONs; SURVEY.md §2 #12)."""

    def __init__(self, period_s: float = 0.05) -> None:
        super().__init__(daemon=True)
        self.period_s = period_s
        self.cpu: list[float] = []
        self.mem: list[float] = []
        self._stop_evt = threading.Event()
        self._clk = os.sysconf("SC_CLK_TCK")
        self._page = os.sysconf("SC_PAGE_SIZE")

    def _read(self) -> tuple[float, float]:
        with open("/proc/self/stat", encoding="ascii") as fh:
            parts = fh.read().rsplit(") ", 1)[1].split()
        # Fields 14/15 (utime/stime) are indices 11/12 after comm.
        cpu_s = (int(parts[11]) + int(parts[12])) / self._clk
        with open("/proc/self/statm", encoding="ascii") as fh:
            rss = int(fh.read().split()[1]) * self._page
        return cpu_s, rss

    def run(self) -> None:
        last_cpu, _ = self._read()
        last_t = time.monotonic()
        while not self._stop_evt.wait(self.period_s):
            cpu_s, rss = self._read()
            now = time.monotonic()
            dt = max(now - last_t, 1e-9)
            self.cpu.append(max(cpu_s - last_cpu, 0.0) / dt)
            self.mem.append(float(rss))
            last_cpu, last_t = cpu_s, now

    def stop(self) -> None:
        self._stop_evt.set()
        self.join(timeout=2.0)


# ---------------------------------------------------------------------------
# Shared scaffolding.
# ---------------------------------------------------------------------------


from kubernetesnetawarescheduler_tpu.core.state import round_up as _round_up


def _make_loop(num_nodes: int, seed: int, weights: ScoreWeights,
               batch: int, max_peers: int = 4, queue: int = 0,
               method: str = "parallel"
               ) -> tuple[SchedulerLoop, SchedulerConfig]:
    cfg = SchedulerConfig(
        max_nodes=_round_up(num_nodes, 128),
        max_pods=batch,
        max_peers=max_peers,
        weights=weights,
        queue_capacity=max(300, queue),
    )
    cluster, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed))
    loop = SchedulerLoop(cluster, cfg, method=method)
    loop.encoder.set_network(lat, bw)
    feed_metrics(cluster, loop.encoder, np.random.default_rng(seed + 1))
    return loop, cfg


def _drain(loop: SchedulerLoop, pods: Sequence[Pod]) -> float:
    """Add + drain; returns wall seconds."""
    start = time.perf_counter()
    loop.client.add_pods(pods)
    loop.run_until_drained()
    return time.perf_counter() - start


def _warm_like(num_nodes: int, seed: int, weights: ScoreWeights,
               batch: int, queue: int) -> None:
    """Pay XLA compilation for the caller's EXACT config outside any
    timed drain: a throwaway loop drains a burst-sized wave then a
    sub-batch wave (the two jit programs), so the caller's drain hits
    the in-process executable cache.  Without this a per-config sweep
    reads compile time as throughput (a measured 76x phantom).

    ``queue`` must equal the caller's queue_capacity: SchedulerConfig
    is the jit STATIC argument, so any differing field — including
    queue_capacity — is a different executable cache key and the warm
    compiles the wrong program."""
    wloop, wcfg = _make_loop(num_nodes, seed + 777, weights,
                             batch=batch, queue=queue)
    for n_warm in (2 * batch, min(batch, 8)):
        warm = generate_workload(
            WorkloadSpec(num_pods=n_warm, seed=seed + 888),
            scheduler_name=wcfg.scheduler_name)
        wloop.client.add_pods(warm)
        wloop.run_until_drained()


# ---------------------------------------------------------------------------
# Config 1 — 100-node clusterloader2 density replay, latency-only score.
# ---------------------------------------------------------------------------


LATENCY_ONLY = ScoreWeights(cpu=0.0, mem=0.0, net_tx=0.0, net_rx=0.0,
                            bandwidth=0.0, disk=0.0,
                            peer_bw=0.0, peer_lat=4.0, balance=0.25)


def run_density_config(out_dir: str | None = None, num_nodes: int = 100,
                       num_pods: int = 1000, batch: int = 64,
                       seed: int = 0) -> SuiteResult:
    """BASELINE config 1: "100-node clusterloader2 density replay
    (netperf latency-only Score)"."""
    cfg = SchedulerConfig(
        max_nodes=_round_up(num_nodes, 128), max_pods=batch, max_peers=4,
        weights=LATENCY_ONLY, queue_capacity=max(300, num_pods + batch))
    # The sampler is handed to run_density, which starts it only after
    # the warmup/compile cycle — the percentiles cover serving, not XLA
    # compilation (matching clusterloader2's sampling of a live system).
    sampler = UsageSampler()
    try:
        res = run_density(num_nodes=num_nodes, num_pods=num_pods,
                          batch_size=batch, seed=seed, cfg=cfg,
                          sampler=sampler)
    finally:
        if sampler.is_alive():
            sampler.stop()
    artifacts = []
    if out_dir:
        path = os.path.join(
            out_dir, f"ResourceUsageSummary_density_{num_nodes}nodes.json")
        write_resource_usage_summary(path, sampler.cpu, sampler.mem)
        artifacts.append(path)
    return SuiteResult("density", {
        "num_nodes": num_nodes,
        "pods_bound": res.pods_bound,
        "pods_per_sec": round(res.pods_per_sec, 1),
        "score_p99_ms": round(res.score_p99_ms, 3),
        "scheduler_cpu_p99_cores": (round(float(np.percentile(
            sampler.cpu, 99)), 4) if sampler.cpu else 0.0),
        "scheduler_mem_p99_bytes": (int(np.percentile(sampler.mem, 99))
                                    if sampler.mem else 0),
    }, artifacts)


# ---------------------------------------------------------------------------
# Config 2 — customNetworkBenchmark replay at 1k nodes.
# ---------------------------------------------------------------------------


def _simulate_transfer_ms(assignments: Sequence[tuple[int, int]],
                          lat: np.ndarray, bw: np.ndarray,
                          data_mb: float) -> float:
    """Completion time (ms) of concurrent ``data_mb`` transfers, one per
    (client_node, server_node) pair; flows sharing a node pair split its
    bandwidth.  Mirrors the reference's measured workload: N pods each
    moving 100 MB, total elapsed committed to the ``.data`` files
    (5podsCustomScheduler.data:2, :5)."""
    if not assignments:
        return 0.0
    flows: dict[tuple[int, int], int] = {}
    for a, b in assignments:
        key = (min(a, b), max(a, b))
        flows[key] = flows.get(key, 0) + 1
    bits = data_mb * 8e6
    worst = 0.0
    for a, b in assignments:
        key = (min(a, b), max(a, b))
        eff_bw = max(bw[a, b] / flows[key], 1.0)
        t_ms = bits / eff_bw * 1e3 + lat[a, b]
        worst = max(worst, float(t_ms))
    return worst


def _spreading_baseline(num_clients: int, loop: SchedulerLoop,
                        rng: np.random.Generator) -> list[int]:
    """The "Original Scheduler" role: a network-oblivious spread over
    ready nodes (what default kube-scheduler's least-allocated spreading
    does to this workload, per the reference's Original*.data runs)."""
    enc = loop.encoder
    ready = [i for i in range(enc.num_nodes) if enc._node_valid[i]]
    rng.shuffle(ready)
    return [ready[i % len(ready)] for i in range(num_clients)]


BW_LAT = ScoreWeights(cpu=0.5, mem=0.5, net_tx=0.0, net_rx=0.0,
                      bandwidth=1.0, disk=0.0,
                      peer_bw=3.0, peer_lat=2.0, balance=0.5)


def run_custom_network_config(out_dir: str | None = None,
                              num_nodes: int = 1024,
                              pod_counts: Sequence[int] = (5, 10),
                              data_mb: float = 100.0,
                              num_servers: int = 3,
                              seed: int = 0,
                              num_seeds: int = 3) -> SuiteResult:
    """BASELINE config 2: "customNetworkBenchmark bandwidth+latency
    weighted score, 1k nodes".

    Server pods land first (the reference's iperf3 server on the master,
    deployment.yaml:17-26); then each client pod declares one server as
    its traffic peer and the scheduler places it; completion is
    simulated on the fake cluster's ground-truth matrices and written in
    the ``.data`` schema, alongside a network-oblivious spreading
    baseline playing the "Original Scheduler" role.

    Averaged over ``num_seeds`` independent clusters: at the
    reference's tiny pod counts a single draw is dominated by WHERE the
    servers happen to land and how lucky the random baseline gets
    (observed single-seed speedups from 1.2× to 17× on the same code),
    so one seed would benchmark the dice, not the scheduler.  The
    ``.data`` files carry the cross-seed mean; per-seed numbers are in
    the metrics."""
    metrics: dict = {"num_nodes": num_nodes, "runs": {}}
    artifacts: list[str] = []
    for n_pods in pod_counts:
        per_seed = []
        affected: list[str] = []
        wall_total = 0.0
        for s_i in range(num_seeds):
            sd = seed + 17 * s_i
            loop, cfg = _make_loop(num_nodes, sd, BW_LAT,
                                   batch=max(n_pods, 8),
                                   queue=n_pods + 16)
            servers = [Pod(name=f"server-{i}",
                           scheduler_name=cfg.scheduler_name,
                           requests={"cpu": 1.0, "mem": 2.0,
                                     "net_bw": 1.0})
                       for i in range(num_servers)]
            _drain(loop, servers)
            server_nodes = {s.name: loop.client.node_of(s.name)
                            for s in servers}
            assert all(server_nodes.values()), "server placement failed"

            rng = np.random.default_rng(sd + n_pods)
            clients = [Pod(name=f"client-{i}",
                           scheduler_name=cfg.scheduler_name,
                           requests={"cpu": 0.25, "mem": 0.5,
                                     "net_bw": 0.5},
                           peers={servers[i % num_servers].name: data_mb})
                       for i in range(n_pods)]
            wall_total += _drain(loop, clients)

            enc = loop.encoder
            lat = enc._lat[:enc.num_nodes, :enc.num_nodes]
            bw = enc._bw[:enc.num_nodes, :enc.num_nodes]
            pairs = []
            for i, c in enumerate(clients):
                node = loop.client.node_of(c.name)
                # A dropped client would silently shrink the custom
                # side's flow set (less bandwidth contention) while
                # the baseline always pays for all n_pods — a
                # structurally inflated speedup, not a measurement.
                assert node, f"client {c.name} unplaced (seed {sd})"
                pairs.append((enc.node_index(node),
                              enc.node_index(server_nodes[
                                  servers[i % num_servers].name])))
            t_custom = _simulate_transfer_ms(pairs, lat, bw, data_mb)

            base_nodes = _spreading_baseline(n_pods, loop, rng)
            base_pairs = [(base_nodes[i],
                           enc.node_index(server_nodes[
                               servers[i % num_servers].name]))
                          for i in range(n_pods)]
            t_orig = _simulate_transfer_ms(base_pairs, lat, bw, data_mb)
            per_seed.append((t_custom, t_orig))
            # Union across seeds: the averaged times come from ALL of
            # these server placements, not just seed 0's.
            affected = sorted(set(affected)
                              | {server_nodes[s.name] for s in servers})

        t_custom = float(np.mean([c for c, _ in per_seed]))
        t_orig = float(np.mean([o for _, o in per_seed]))
        if out_dir:
            pc = os.path.join(out_dir, f"{n_pods}podsCustomScheduler.data")
            po = os.path.join(out_dir, f"{n_pods}podsOriginalScheduler.data")
            write_data_file(pc, n_pods, data_mb, affected, t_custom)
            write_data_file(po, n_pods, data_mb, affected, t_orig)
            artifacts += [pc, po]
        metrics["runs"][str(n_pods)] = {
            "custom_ms": round(t_custom, 1),
            "original_ms": round(t_orig, 1),
            "speedup": round(t_orig / t_custom, 2) if t_custom else 0.0,
            "per_seed": [
                {"custom_ms": round(c, 1), "original_ms": round(o, 1),
                 "speedup": round(o / c, 2) if c else 0.0}
                for c, o in per_seed],
            "schedule_wall_s": round(wall_total / num_seeds, 3),
        }
    return SuiteResult("custom_network", metrics, artifacts)


# ---------------------------------------------------------------------------
# Config 3 — affinity/anti-affinity constraint masks.
# ---------------------------------------------------------------------------


def check_constraint_violations(loop: SchedulerLoop,
                                pods: Sequence[Pod]) -> dict[str, int]:
    """Host-side (oracle) audit that no bound pod violates its hard
    constraints — the property the batched ``-inf`` masks plus the
    conflict resolver guarantee (SURVEY.md §4(e))."""
    client = loop.client
    by_node: dict[str, list[Pod]] = {}
    for p in pods:
        node = client.node_of(p.name)
        if node:
            by_node.setdefault(node, []).append(p)
    nodes = {n.name: n for n in client.list_nodes()}
    viol = {"affinity": 0, "anti": 0, "taint": 0, "capacity": 0,
            "zone_affinity": 0, "zone_anti": 0, "node_affinity": 0}
    # Realized per-(zone, group) member counts (zone-scoped
    # constraints).  Final-state audit: members never move or
    # terminate in these workloads, so it never reports FALSE
    # violations; for zone affinity it can under-detect (a service
    # mate placed later makes an originally-empty zone look
    # satisfied) — placement-time exactness is the oracle/property
    # tests' job, this audit catches the blatant invariant breaks at
    # bench scale.
    zone_of = {name: n.zone for name, n in nodes.items()}
    zg_count: dict[tuple[str, str], int] = {}
    for node_name, placed in by_node.items():
        z = zone_of.get(node_name, "")
        if z:
            for p in placed:
                if p.group:
                    key = (z, p.group)
                    zg_count[key] = zg_count.get(key, 0) + 1

    def _members(z: str, group: str, exclude_self_of=None) -> int:
        c = zg_count.get((z, group), 0)
        if exclude_self_of is not None and exclude_self_of.group == group:
            c -= 1  # a pod is not its own zone-affinity witness
        return c

    def _expr_ok(op: str, key: str, vals, labels: dict) -> bool:
        if op == "In":
            return labels.get(key) in vals
        if op == "NotIn":
            return labels.get(key) not in vals
        if op == "Exists":
            return key in labels
        if op == "DoesNotExist":
            return key not in labels
        return False

    # kube's first-pod waiver: a required self-affinity term with no
    # member anywhere is waived for ONE pod per (group, scope) — such
    # orphans are collected and bounded instead of counted as
    # violations (mirrors tests/test_encode_fuzz.py's checker).
    orphans: dict[tuple, int] = {}
    for node_name, placed in by_node.items():
        node = nodes[node_name]
        z = zone_of.get(node_name, "")
        labels = dict(s.split("=", 1) for s in node.labels if "=" in s)
        for p in placed:
            for g in p.zone_affinity_groups:
                if z and _members(z, g, exclude_self_of=p) > 0:
                    continue  # term satisfied (zone terms AND)
                if g == p.group:
                    orphans[("zone", g)] = orphans.get(("zone", g),
                                                      0) + 1
                else:
                    viol["zone_affinity"] += 1
            if z and any(_members(z, g, exclude_self_of=p) > 0
                         for g in p.zone_anti_groups):
                # Self-exclusion: a pod with anti-affinity against its
                # OWN group (kube's one-per-zone pattern) is not its
                # own violation witness.
                viol["zone_anti"] += 1
            if p.required_node_affinity and not any(
                    all(_expr_ok(op, key, vals, labels)
                        for op, key, vals in term)
                    for term in p.required_node_affinity):
                viol["node_affinity"] += 1
        for p in placed:
            # Groups of the OTHER residents: required affinity must be
            # satisfied by a co-resident (the kernel checks group_bits
            # *before* the pod lands, so self never satisfies it) for
            # EVERY term (terms AND, kube's join), and anti-affinity
            # means no co-resident's group is forbidden — including
            # the pod's own group (spread semantics), matching
            # feasibility_mask + the symmetric resident_anti check.
            others = {q.group for q in placed if q is not p and q.group}
            for g in p.affinity_groups:
                if g in others:
                    continue
                if g == p.group:
                    orphans[("host", g)] = orphans.get(("host", g),
                                                       0) + 1
                else:
                    viol["affinity"] += 1
            if set(p.anti_groups) & others:
                viol["anti"] += 1
            if node.taints - p.tolerations:
                viol["taint"] += 1
        for rname in ("cpu", "mem", "net_bw"):
            used = sum(p.requests.get(rname, 0.0) for p in placed)
            if used > node.capacity.get(rname, 0.0) + 1e-6:
                viol["capacity"] += 1
    # A second memberless self-affine pod per (scope, group) means the
    # waiver leaked — THAT is a violation.
    for key, count in orphans.items():
        if count > 1:
            viol["affinity" if key[0] == "host"
                 else "zone_affinity"] += count - 1
    return viol


def run_affinity_config(out_dir: str | None = None, num_nodes: int = 512,
                        num_pods: int = 2048, batch: int = 128,
                        seed: int = 0) -> SuiteResult:
    """BASELINE config 3: "inter-pod affinity/anti-affinity as batched
    constraint masks"."""
    loop, cfg = _make_loop(num_nodes, seed, ScoreWeights(), batch=batch,
                           queue=num_pods + batch)
    pods = generate_workload(
        WorkloadSpec(num_pods=num_pods, services=24, affinity_fraction=0.4,
                     anti_fraction=0.25, seed=seed),
        scheduler_name=cfg.scheduler_name)
    wall = _drain(loop, pods)
    viol = check_constraint_violations(loop, pods)
    metrics = {
        "num_nodes": num_nodes,
        "pods_bound": loop.scheduled,
        "pods_unschedulable": loop.unschedulable,
        "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
        "violations": viol,
        "violations_total": sum(viol.values()),
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "affinity_audit.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("affinity", metrics, artifacts)


def _zone_pref_stats(loop, pods) -> tuple[int, int]:
    """(placed_prefer, satisfied) from FINAL placements."""
    zones = {n.name: n.zone for n in loop.client.list_nodes()}
    satisfied = placed_prefer = 0
    for p in pods:
        if not p.soft_node_affinity:
            continue
        node = loop.client.node_of(p.name)
        if not node:
            continue
        placed_prefer += 1
        (labels, _w), = p.soft_node_affinity
        want_zone = next(iter(labels)).split("=", 1)[1]
        if zones[node] == f"zone-{want_zone}":
            satisfied += 1
    return placed_prefer, satisfied


def _zone_attainable(loop, pods, free0) -> int:
    """Capacity-aware attainable optimum (VERDICT r3 next-round #6):
    replay the SUBMISSION order against the starting free capacity —
    a zone preference counts as attainable when, at that pod's turn
    (with every earlier pod's usage applied at its REAL node), the
    preferred zone still had a node that fits the pod.  Preferences
    whose zone was already full are not losses."""
    from kubernetesnetawarescheduler_tpu.core.encode import (
        _requests_vector,
    )

    zone_of_idx: dict[int, str] = {}
    for n in loop.client.list_nodes():
        try:
            zone_of_idx[loop.encoder.node_index(n.name)] = n.zone
        except KeyError:
            pass
    free = free0.copy()
    attainable = 0
    for p in pods:
        node = loop.client.node_of(p.name)
        if not node:
            continue
        req = _requests_vector(p.requests, free.shape[1])
        if p.soft_node_affinity:
            (labels, _w), = p.soft_node_affinity
            want = f"zone-{next(iter(labels)).split('=', 1)[1]}"
            for idx, zone in zone_of_idx.items():
                if zone == want and np.all(req <= free[idx] + 1e-6):
                    attainable += 1
                    break
        free[loop.encoder.node_index(node)] -= req
    return attainable


def _zone_trade_analysis(num_nodes: int, seed: int, weights,
                         spec) -> dict:
    """Why attainable preferences go unsatisfied (VERDICT r4 #8).

    Sequential replay (ONE pod per decision, the production scorer,
    peers resolved against real placements) with score introspection
    at every decision:

    - For each attainable-but-unsatisfied preference: the CHOSEN
      node's margin over the preferred zone's best feasible node —
      what forcing the preference would sacrifice in other terms.
    - ``traded_to_network``: misses where re-scoring with
      ``peer_bw``/``peer_lat`` zeroed flips the argmax INTO the
      preferred zone.  Round-5 root cause: the dominant outbidder is
      the NETWORK-AFFINITY term — the scheduler's headline capability
      pulls pods toward nodes with good bandwidth/latency to their
      already-placed service peers (measured +8..+17 score units),
      which beats a 1.6-4.0-unit zone bonus at default weights.
      That is the intended precedence for a network-aware scheduler
      and the knob is ``ScoreWeights.peer_*`` vs ``soft_affinity``.
      (An earlier draft of this analysis passed ``node_of=""`` —
      peers never resolved, the network term silently zeroed — and
      concluded preferences were never traded.  With peers OFF the
      scorer does satisfy ~100% of attainable preferences, which is
      now the ``sequential_vs_optimum_peers_off`` control below.)
    """
    from kubernetesnetawarescheduler_tpu.core.assign import (
        assign_greedy,
    )
    from kubernetesnetawarescheduler_tpu.core.score import (
        NEG_INF,
        score_pods,
    )

    import dataclasses as _dc

    import jax

    from kubernetesnetawarescheduler_tpu.k8s.types import Binding

    def _run(resolve_peers: bool) -> dict:
        loop, cfg = _make_loop(num_nodes, seed, weights, batch=1,
                               queue=8)
        cfg_nopeer = _dc.replace(
            cfg, weights=_dc.replace(cfg.weights, peer_bw=0.0,
                                     peer_lat=0.0))
        pods = generate_workload(spec,
                                 scheduler_name=cfg.scheduler_name)
        loop.client.add_pods(pods)
        zone_of_idx: dict[int, str] = {}
        for n in loop.client.list_nodes():
            try:
                zone_of_idx[loop.encoder.node_index(n.name)] = n.zone
            except KeyError:
                pass
        # Jit once (cfg closed over): op-by-op dispatch measured
        # ~0.9 s per pod on CPU; compiled, the pass is seconds.
        score_j = jax.jit(lambda s, b: score_pods(s, b, cfg))
        score_np = jax.jit(lambda s, b: score_pods(s, b, cfg_nopeer))
        assign_j = jax.jit(lambda s, b: assign_greedy(s, b, cfg))
        node_of = (loop._peer_node if resolve_peers
                   else (lambda n: ""))
        scale = weights.soft_affinity / 100.0
        placed = attain = sat = to_net = 0
        margins: list[float] = []
        bonuses: list[float] = []
        for p in pods:
            enc = loop.encoder.encode_pods([p], node_of=node_of,
                                           lenient=True)
            st = loop.encoder.snapshot()
            row = np.asarray(score_j(st, enc))[0]
            feasible = row > NEG_INF / 2
            chosen = int(np.asarray(assign_j(st, enc))[0])
            if chosen < 0:
                continue
            loop.encoder.commit_many([p], [chosen])
            # Record the placement so later pods' peers resolve.
            loop.client.bind(Binding(
                pod_name=p.name, namespace=p.namespace,
                node_name=loop.encoder.node_name(chosen)))
            if not p.soft_node_affinity:
                continue
            placed += 1
            (labels, w), = p.soft_node_affinity
            want = f"zone-{next(iter(labels)).split('=', 1)[1]}"
            zone_idxs = [i for i, z in zone_of_idx.items()
                         if z == want and feasible[i]]
            if not zone_idxs:
                continue
            attain += 1
            if zone_of_idx.get(chosen) == want:
                sat += 1
            else:
                best_pref = max(zone_idxs,
                                key=lambda i: float(row[i]))
                margins.append(float(row[chosen] - row[best_pref]))
                bonuses.append(scale * float(w))
                row_np = np.asarray(score_np(st, enc))[0]
                if zone_of_idx.get(int(np.argmax(row_np))) == want:
                    to_net += 1
        return {
            "placed_prefer": placed,
            "attainable": attain,
            "satisfied": sat,
            "vs_optimum": round(sat / attain, 3) if attain else 0.0,
            "traded": len(margins),
            "traded_to_network": to_net,
            "margin_p50": round(float(np.percentile(margins, 50)), 2)
            if margins else 0.0,
            "margin_p90": round(float(np.percentile(margins, 90)), 2)
            if margins else 0.0,
            "zone_bonus_mean": round(float(np.mean(bonuses)), 2)
            if bonuses else 0.0,
        }

    out = {f"sequential_{k}": v for k, v in _run(True).items()}
    out["sequential_vs_optimum_peers_off"] = \
        _run(False)["vs_optimum"]
    return out


def run_soft_affinity_config(out_dir: str | None = None,
                             num_nodes: int = 256, num_pods: int = 1024,
                             batch: int = 128, seed: int = 0,
                             deep: bool = True) -> SuiteResult:
    """Preferred (soft) affinity under load: pods carry weighted zone
    preferences (``preferredDuringSchedulingIgnoredDuringExecution``
    nodeAffinity semantics, the stanza the reference's probe server
    used — netperfScript/deployment.yaml:17-26) and weighted spread
    preferences (negative soft group affinity).

    Audited outcomes: the fraction of zone-preferring pods landing in
    their preferred zone (soft pull), and same-node co-location of
    spread-preferring pods vs. a control run with the soft term
    disabled (soft push).  Hard-constraint audit stays green — soft
    terms bias scores, never override masks."""
    weights = ScoreWeights(soft_affinity=4.0)
    loop, cfg = _make_loop(num_nodes, seed, weights, batch=batch,
                           queue=num_pods + batch)
    # Zone count comes from the same ClusterSpec default _make_loop
    # builds with, so workload preferences always target zones that
    # exist on the cluster.
    spec = WorkloadSpec(num_pods=num_pods, soft_zone_fraction=0.5,
                        soft_spread_fraction=0.3,
                        zones=ClusterSpec().zones, seed=seed)
    pods = generate_workload(spec, scheduler_name=cfg.scheduler_name)
    state_initial = loop.encoder.snapshot()
    free0 = np.asarray(state_initial.cap - state_initial.used).copy()
    _warm_like(num_nodes, seed, weights, batch,
               queue=num_pods + batch)  # compile off-window
    wall = _drain(loop, pods)

    placed_prefer, satisfied = _zone_pref_stats(loop, pods)
    attainable = _zone_attainable(loop, pods, free0)

    def _max_colocation(workload: Sequence[Pod], lp) -> float:
        """Mean over spread-preferring pods of same-group co-residents
        on their node (lower = better spreading)."""
        by_node: dict[str, list[Pod]] = {}
        for p in workload:
            node = lp.client.node_of(p.name)
            if node:
                by_node.setdefault(node, []).append(p)
        counts = []
        for p in workload:
            if not p.soft_group_affinity:
                continue
            node = lp.client.node_of(p.name)
            if not node:
                continue
            counts.append(sum(1 for q in by_node[node]
                              if q is not p and q.group == p.group))
        return float(np.mean(counts)) if counts else 0.0

    coloc = _max_colocation(pods, loop)
    # Control: identical workload, soft term off.
    control_loop, ccfg = _make_loop(num_nodes, seed,
                                    ScoreWeights(soft_affinity=0.0),
                                    batch=batch, queue=num_pods + batch)
    control_pods = generate_workload(spec,
                                     scheduler_name=ccfg.scheduler_name)
    _drain(control_loop, control_pods)
    coloc_control = _max_colocation(control_pods, control_loop)
    viol = check_constraint_violations(loop, pods)
    metrics = {
        "num_nodes": num_nodes,
        "pods_bound": loop.scheduled,
        "pods_unschedulable": loop.unschedulable,
        "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
        "zone_pref_pods": placed_prefer,
        "zone_pref_satisfied": satisfied,
        "zone_pref_rate": round(satisfied / placed_prefer, 3)
        if placed_prefer else 0.0,
        # Falsifiable bar: attainable optimum + achieved/attainable.
        "zone_pref_attainable": attainable,
        "zone_pref_optimum_rate": round(attainable / placed_prefer, 3)
        if placed_prefer else 0.0,
        "zone_pref_vs_optimum": round(satisfied / attainable, 3)
        if attainable else 0.0,
        "spread_colocation": round(coloc, 3),
        "spread_colocation_control": round(coloc_control, 3),
        "violations_total": sum(viol.values()),
    }
    if deep:
        # Why achieved < attainable (VERDICT r4 #8): batch-conflict
        # vs deliberate score trades, decision-time margins, and the
        # weight knob's response curve — the same falsifiability the
        # sidecar audit has.
        metrics["zone_pref_trade"] = _zone_trade_analysis(
            num_nodes, seed, weights, spec)
        sweep = []
        sweep_points = [ScoreWeights(soft_affinity=w)
                        for w in (2.0, 8.0, 16.0)]
        # The falsifying control: the same drain with the NETWORK
        # term off.  If the misses are network-over-preference trades
        # (they are — see zone_pref_trade), this entry jumps toward
        # the attainable optimum.
        sweep_points.append(ScoreWeights(soft_affinity=4.0,
                                         peer_bw=0.0, peer_lat=0.0))
        for sw in sweep_points:
            w = sw.soft_affinity
            sl, scfg_ = _make_loop(num_nodes, seed, sw, batch=batch,
                                   queue=num_pods + batch)
            spods = generate_workload(
                spec, scheduler_name=scfg_.scheduler_name)
            st0 = sl.encoder.snapshot()
            sfree0 = np.asarray(st0.cap - st0.used).copy()
            _drain(sl, spods)
            sp, ss = _zone_pref_stats(sl, spods)
            sa = _zone_attainable(sl, spods, sfree0)
            sviol = check_constraint_violations(sl, spods)
            entry = {
                "soft_affinity_weight": w,
                "zone_pref_vs_optimum": round(ss / sa, 3) if sa
                else 0.0,
                "spread_colocation": round(
                    _max_colocation(spods, sl), 3),
                "violations_total": sum(sviol.values()),
            }
            if sw.peer_bw == 0.0 and sw.peer_lat == 0.0:
                entry["network_term"] = "off (control)"
            sweep.append(entry)
        sweep.append({
            "soft_affinity_weight": weights.soft_affinity,
            "zone_pref_vs_optimum": metrics["zone_pref_vs_optimum"],
            "spread_colocation": metrics["spread_colocation"],
            "violations_total": metrics["violations_total"],
            "default": True,
        })
        metrics["zone_pref_weight_sweep"] = sorted(
            sweep, key=lambda r: r["soft_affinity_weight"])
        # The other axis: batch size.  The sequential pass proves the
        # SCORING satisfies every attainable preference; what remains
        # is batch-conflict dynamics (one snapshot scores the whole
        # batch; same-zone competitors race, losers settle elsewhere
        # in-round).  This sweep commits the throughput <-> preference
        # frontier an operator actually tunes.
        bsweep = []
        for b in (8, 32, batch):
            bl, bcfg = _make_loop(num_nodes, seed, weights, batch=b,
                                  queue=num_pods + b)
            bpods = generate_workload(
                spec, scheduler_name=bcfg.scheduler_name)
            bst0 = bl.encoder.snapshot()
            bfree0 = np.asarray(bst0.cap - bst0.used).copy()
            _warm_like(num_nodes, seed, weights, b,
                       queue=num_pods + b)
            bwall = _drain(bl, bpods)
            bp, bs = _zone_pref_stats(bl, bpods)
            ba = _zone_attainable(bl, bpods, bfree0)
            bsweep.append({
                "batch": b,
                "zone_pref_vs_optimum": round(bs / ba, 3) if ba
                else 0.0,
                "pods_per_sec": round(bl.scheduled / bwall, 1)
                if bwall else 0.0,
                "default": b == batch,
            })
        metrics["zone_pref_batch_sweep"] = bsweep
        # The conclusion is DERIVED from this run's own measurements,
        # not asserted: a seed/shape where the network term is not
        # the dominant outbidder must not ship the round-5 narrative
        # verbatim next to numbers that contradict it.
        trade = metrics["zone_pref_trade"]
        net_frac = (trade["sequential_traded_to_network"]
                    / trade["sequential_traded"]
                    if trade["sequential_traded"] else 1.0)
        rates = [r["zone_pref_vs_optimum"] for r in bsweep]
        batch_flat = (max(rates) - min(rates) < 0.1) if rates else True
        if net_frac >= 0.9:
            concl = (f"{net_frac:.0%} of unsatisfied attainable zone "
                     "preferences flip into their zone when "
                     "peer_bw/peer_lat are zeroed: the misses are "
                     "deliberate weighted trades won by the network-"
                     "affinity term (margin_p50 "
                     f"{trade['sequential_margin_p50']} score units "
                     "vs zone bonus "
                     f"{trade['sequential_zone_bonus_mean']}); the "
                     "peers-off controls recover ~the attainable "
                     "optimum.  Knob: ScoreWeights.peer_* vs "
                     "soft_affinity.")
        else:
            concl = (f"only {net_frac:.0%} of misses are network-"
                     "term trades this run — see zone_pref_trade "
                     "margins and the weight sweep for the rest.")
        concl += (" Batching is not a factor (vs_optimum flat across "
                  "batch sizes)." if batch_flat else
                  " Batch size MATTERS this run — see "
                  "zone_pref_batch_sweep.")
        metrics["zone_pref_conclusion"] = concl
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "soft_affinity_audit.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("soft_affinity", metrics, artifacts)


def run_spread_config(out_dir: str | None = None, num_nodes: int = 256,
                      num_pods: int = 1024, batch: int = 128,
                      seed: int = 0) -> SuiteResult:
    """Topology spread under load: a mixed workload where some
    services carry zone-level topologySpreadConstraints (hard AND
    soft).  Audited outcome: for every hard-constrained service, the
    realized zone skew of its placed pods never exceeds its maxSkew —
    the kube PodTopologySpread invariant, enforced here by the batched
    masks plus the per-round winner cap (assign_parallel).

    The exact-final-histogram audit assumes placements are never
    undone mid-run: preemption stays at its default (disabled) here —
    an eviction from the min-count zone could legitimately leave the
    survivors' skew above the bound with no scheduler bug."""
    loop, cfg = _make_loop(num_nodes, seed, ScoreWeights(), batch=batch,
                           queue=num_pods + batch)
    spec = WorkloadSpec(num_pods=num_pods, spread_fraction=0.5,
                        spread_hard_fraction=0.5, seed=seed)
    pods = generate_workload(spec, scheduler_name=cfg.scheduler_name)
    wall = _drain(loop, pods)

    zones = {n.name: n.zone for n in loop.client.list_nodes()}
    # Realized per-(group, zone) placement of hard-constrained
    # services (constraints are uniform per service — the Deployment-
    # template shape — so every member placement was skew-checked and
    # the final distribution must satisfy the bound exactly).
    by_group: dict[str, dict[str, int]] = {}
    skew_bound: dict[str, int] = {}
    for p in pods:
        if p.spread_maxskew <= 0 or not p.spread_hard:
            continue
        node = loop.client.node_of(p.name)
        if not node:
            continue
        hist = by_group.setdefault(p.group, {})
        hist[zones[node]] = hist.get(zones[node], 0) + 1
        skew_bound[p.group] = p.spread_maxskew
    all_zones = sorted(set(zones.values()))
    violations = 0
    worst_skew = 0
    for grp, hist in by_group.items():
        counts = [hist.get(z, 0) for z in all_zones]
        skew = max(counts) - min(counts)
        worst_skew = max(worst_skew, skew)
        if skew > skew_bound[grp]:
            violations += 1
    metrics = {
        "num_nodes": num_nodes,
        "pods_bound": loop.scheduled,
        "pods_unschedulable": loop.unschedulable,
        "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
        "hard_spread_groups": len(by_group),
        "worst_zone_skew": worst_skew,
        "skew_violations": violations,
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "spread_audit.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("spread", metrics, artifacts)


# ---------------------------------------------------------------------------
# Config 4 — multi-resource bin-packing with soft penalties.
# ---------------------------------------------------------------------------


def _utilization(loop: SchedulerLoop) -> np.ndarray:
    enc = loop.encoder
    n = enc.num_nodes
    cap = np.maximum(enc._cap[:n], 1e-9)
    return (enc._used[:n] / cap).max(axis=1)


def run_binpack_config(out_dir: str | None = None, num_nodes: int = 256,
                       num_pods: int = 4096, batch: int = 128,
                       seed: int = 0) -> SuiteResult:
    """BASELINE config 4: "multi-resource bin-packing (CPU/mem/net-bw
    caps) with soft penalties".

    Runs the same near-saturating workload with the balance penalty ON
    and OFF; reports overcommit (must be zero — the hard caps are part
    of the feasibility mask) and the worst-fit utilization spread the
    soft penalty is there to flatten."""
    results = {}
    for label, w in (("balanced", ScoreWeights(balance=4.0)),
                     ("unbalanced", ScoreWeights(balance=0.0))):
        loop, cfg = _make_loop(num_nodes, seed, w, batch=batch,
                               queue=num_pods + batch)
        pods = generate_workload(
            WorkloadSpec(num_pods=num_pods, services=32, peer_fraction=0.3,
                         cpu_range=(0.5, 4.0), mem_range=(1.0, 16.0),
                         seed=seed),
            scheduler_name=cfg.scheduler_name)
        wall = _drain(loop, pods)
        util = _utilization(loop)
        viol = check_constraint_violations(loop, pods)
        results[label] = {
            "pods_bound": loop.scheduled,
            "pods_unschedulable": loop.unschedulable,
            "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
            "overcommit_nodes": int((util > 1.0 + 1e-6).sum()),
            "capacity_violations": viol["capacity"],
            "util_p99": round(float(np.percentile(util, 99)), 4),
            "util_std": round(float(util.std()), 4),
        }
    metrics = {"num_nodes": num_nodes, **results}
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "binpack_audit.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("binpack", metrics, artifacts)


# ---------------------------------------------------------------------------
# Config 5 — service-mesh sidecar co-placement, 5k nodes.
# ---------------------------------------------------------------------------


def generate_mesh_workload(num_apps: int, services: int,
                           scheduler_name: str, seed: int = 0
                           ) -> tuple[list[Pod], list[Pod]]:
    """An Istio-style topology: ``services`` tiers in a chain
    (frontend -> ... -> backend); each app pod talks to pods of its
    upstream tier; each app pod has one sidecar pod whose traffic to its
    app dwarfs everything else (the Envoy-next-to-workload shape)."""
    rng = np.random.default_rng(seed)
    apps: list[Pod] = []
    by_tier: dict[int, list[str]] = {}
    for i in range(num_apps):
        tier = int(rng.integers(0, services))
        name = f"app-{tier:02d}-{i:05d}"
        peers = {}
        upstream = by_tier.get(tier - 1, [])
        if upstream:
            for j in rng.choice(len(upstream),
                                size=min(2, len(upstream)), replace=False):
                peers[upstream[int(j)]] = float(rng.uniform(1.0, 5.0))
        apps.append(Pod(
            name=name, scheduler_name=scheduler_name,
            requests={"cpu": float(rng.uniform(0.5, 2.0)),
                      "mem": float(rng.uniform(1.0, 4.0)),
                      "net_bw": 0.2},
            peers=peers, group=f"tier-{tier}"))
        by_tier.setdefault(tier, []).append(name)
    sidecars = [Pod(
        name=f"sidecar-{app.name}", scheduler_name=scheduler_name,
        requests={"cpu": 0.1, "mem": 0.25, "net_bw": 0.05},
        peers={app.name: 100.0}) for app in apps]
    return apps, sidecars


def run_sidecar_config(out_dir: str | None = None, num_nodes: int = 5120,
                       num_apps: int = 1024, batch: int = 128,
                       seed: int = 0) -> SuiteResult:
    """BASELINE config 5: "service-mesh sidecar co-placement (Istio
    topology graph, 5k nodes)".

    Sidecar→app co-location is pure network scoring: the ``C[N, N]``
    diagonal is pinned to loopback-best
    (:func:`~kubernetesnetawarescheduler_tpu.core.score.net_cost_matrix`),
    so a sidecar with a dominant peer lands on that peer's node unless
    capacity masks forbid it — then same-rack is next best."""
    loop, cfg = _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                           queue=2 * num_apps + batch)
    apps, sidecars = generate_mesh_workload(num_apps, services=6,
                                            scheduler_name=cfg.scheduler_name,
                                            seed=seed)
    wall_apps = _drain(loop, apps)
    # Post-app free capacity snapshot: the basis of the ATTAINABLE
    # co-placement optimum below (VERDICT r3 next-round #6 — an audit
    # without a falsifiable bar cannot distinguish a capacity-bound
    # 0.72 from a real loss).
    state_after_apps = loop.encoder.snapshot()
    free_after_apps = np.asarray(state_after_apps.cap
                                 - state_after_apps.used).copy()
    wall_side = _drain(loop, sidecars)

    nodes = {n.name: n for n in loop.client.list_nodes()}
    co_node = co_rack = placed = 0
    # Capacity-aware attainable optimum: walk the (app, sidecar) pairs
    # in schedule order and greedily place each sidecar on its app's
    # node whenever it still fits — the co-placement count a scheduler
    # that cared about NOTHING else could reach given these app
    # placements.  The real scheduler also balances load and serves
    # whole batches at once, so rate/optimum is the honest score.
    from kubernetesnetawarescheduler_tpu.core.encode import (
        _requests_vector,
    )

    free = free_after_apps
    attainable = 0
    for app, side in zip(apps, sidecars):
        an = loop.client.node_of(app.name)
        if not an:
            continue
        ai = loop.encoder.node_index(an)
        req = _requests_vector(side.requests, free.shape[1])
        if np.all(req <= free[ai] + 1e-6):
            attainable += 1
            free[ai] -= req
    for app, side in zip(apps, sidecars):
        an = loop.client.node_of(app.name)
        sn = loop.client.node_of(side.name)
        if not an or not sn:
            continue
        placed += 1
        if an == sn:
            co_node += 1
        if nodes[an].rack == nodes[sn].rack:
            co_rack += 1
    wall = wall_apps + wall_side
    rate = round(co_node / placed, 4) if placed else 0.0
    optimum = round(attainable / placed, 4) if placed else 0.0
    metrics = {
        "num_nodes": num_nodes,
        "apps": len(apps),
        "sidecar_pairs_placed": placed,
        "coplaced_same_node": co_node,
        "coplaced_same_rack": co_rack,
        "coplacement_rate": rate,
        "same_rack_rate": round(co_rack / placed, 4) if placed else 0.0,
        # Falsifiable bar: attainable optimum + achieved/attainable.
        "coplacement_attainable": attainable,
        "coplacement_optimum_rate": optimum,
        "coplacement_vs_optimum": round(co_node / attainable, 4)
        if attainable else 0.0,
        "pods_per_sec": (round(loop.scheduled / wall, 1) if wall else 0.0),
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "sidecar_coplacement.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("sidecar", metrics, artifacts)


# ---------------------------------------------------------------------------
# Runner.
# ---------------------------------------------------------------------------


def run_zone_affinity_config(out_dir: str | None = None,
                             num_nodes: int = 256, num_pods: int = 2048,
                             batch: int = 128, seed: int = 0
                             ) -> SuiteResult:
    """Zone-scoped hard pod (anti-)affinity + nodeAffinity
    matchExpressions under load: followers join their service's zone,
    zone-anti pods avoid zones hosting their forbidden service, and
    disk-constrained pods land only on matching nodes — audited
    against realized placements (``check_constraint_violations`` zone/
    node_affinity rows must be zero)."""
    loop, cfg = _make_loop(num_nodes, seed, ScoreWeights(), batch=batch,
                           queue=num_pods + batch)
    pods = generate_workload(
        WorkloadSpec(num_pods=num_pods, services=24,
                     zone_aff_fraction=0.15, zone_anti_fraction=0.1,
                     ns_fraction=0.2, affinity_fraction=0.1,
                     anti_fraction=0.1, seed=seed),
        scheduler_name=cfg.scheduler_name)
    wall = _drain(loop, pods)
    viol = check_constraint_violations(loop, pods)
    n_zaff = sum(1 for p in pods if p.zone_affinity_groups)
    n_zanti = sum(1 for p in pods if p.zone_anti_groups)
    n_ns = sum(1 for p in pods if p.required_node_affinity)
    metrics = {
        "num_nodes": num_nodes,
        "pods_bound": loop.scheduled,
        "pods_unschedulable": loop.unschedulable,
        "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
        "zone_aff_pods": n_zaff,
        "zone_anti_pods": n_zanti,
        "node_affinity_pods": n_ns,
        "violations": viol,
        "violations_total": sum(viol.values()),
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "zone_affinity_audit.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("zone_affinity", metrics, artifacts)


def run_gang_config(out_dir: str | None = None, num_nodes: int = 5120,
                    num_gangs: int = 24,
                    member_counts: Sequence[int] = (8, 16, 32),
                    filler_pods: int = 256, batch: int = 128,
                    overhead_pods: int = 512,
                    seed: int = 0) -> SuiteResult:
    """Gang scheduling leg (core/gang.py): mixed 8/16/32-member TPU
    slice jobs at N=5120, interleaved with independent filler pods.

    Reports three falsifiable bars:

    - atomicity: every submitted gang ends fully Bound (no strict
      subset — the fake apiserver's ``bind_gang`` transaction plus the
      loop's rollback path make a partial gang a bug, not a tail);
    - network quality: mean intra-gang pairwise bandwidth (ground-
      truth ``bw`` matrix, loopback for co-located pairs) must be
      STRICTLY higher than an independent baseline — the same pods
      with their gang annotations stripped, on an identical fresh
      cluster;
    - gate overhead: a gang-free workload through a gang-enabled loop
      must stay within 10% of the same workload with
      ``enable_gang_scheduling=False`` (the gate is a per-pod
      annotation probe; pods without it must not pay for the feature).

    Gang latency p50/p99 comes from polling each gang's registry phase
    between scheduling cycles — latency is measured from workload
    submission to the cycle after the gang's atomic bind lands.
    """
    import dataclasses as _dc

    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
        generate_gang_workload,
    )
    from kubernetesnetawarescheduler_tpu.core.gang import (
        BOUND,
        gang_key_of,
        mean_intra_gang_bw,
    )

    def _gang_loop(sd: int, enable: bool = True
                   ) -> tuple[SchedulerLoop, SchedulerConfig, np.ndarray]:
        cfg = SchedulerConfig(
            max_nodes=_round_up(num_nodes, 128),
            max_pods=batch,
            max_peers=4,
            weights=BW_LAT,
            queue_capacity=max(300, 4 * batch
                               + num_gangs * max(member_counts)
                               + filler_pods + overhead_pods),
            enable_gang_scheduling=enable,
        )
        cluster, lat, bw = build_fake_cluster(
            ClusterSpec(num_nodes=num_nodes, seed=sd))
        loop = SchedulerLoop(cluster, cfg, method="parallel")
        loop.encoder.set_network(lat, bw)
        feed_metrics(cluster, loop.encoder, np.random.default_rng(sd + 1))
        return loop, cfg, bw

    def _drain_tracking_gangs(loop: SchedulerLoop, pods: Sequence[Pod],
                              keys: Sequence[str]
                              ) -> tuple[float, dict[str, float]]:
        t0 = time.perf_counter()
        loop.client.add_pods(pods)
        bound_at: dict[str, float] = {}
        for _ in range(10_000):
            n = loop.run_once(timeout=0.0)
            if loop.gangs is not None:
                now = time.perf_counter() - t0
                for key in keys:
                    if key not in bound_at \
                            and loop.gangs.phase_of(key) == BOUND:
                        bound_at[key] = now
            if n == 0 and len(loop.queue) == 0:
                loop.flush_binds()
                if len(loop.queue) == 0:
                    break
        return time.perf_counter() - t0, bound_at

    def _member_node_idx(loop: SchedulerLoop,
                         members: Sequence[Pod]) -> np.ndarray:
        idx = []
        for p in members:
            node = loop.client.node_of(p.name)
            idx.append(loop.encoder.node_index(node) if node else -1)
        return np.asarray(idx, np.int32)

    pods = generate_gang_workload(
        num_gangs=num_gangs, member_counts=member_counts,
        filler_pods=filler_pods, seed=seed)
    by_gang: dict[str, list[Pod]] = {}
    for p in pods:
        key = gang_key_of(p)
        if key:
            by_gang.setdefault(key, []).append(p)
    gang_keys = sorted(by_gang)

    # Warm the jit cache for this EXACT cfg on a throwaway loop so the
    # timed drains (and the gang latency percentiles) measure
    # scheduling, not XLA compilation.
    wloop, wcfg, _ = _gang_loop(seed + 777)
    for n_warm in (2 * batch, min(batch, 8)):
        wpods = generate_workload(
            WorkloadSpec(num_pods=n_warm, seed=seed + 888),
            scheduler_name=wcfg.scheduler_name)
        wloop.client.add_pods(wpods)
        wloop.run_until_drained()
    # One gang per member size: the biased re-score pass is a distinct
    # jit program per padded gang shape.
    wgang = generate_gang_workload(
        num_gangs=len(member_counts), member_counts=member_counts,
        seed=seed + 999, scheduler_name=wcfg.scheduler_name)
    wloop.client.add_pods(wgang)
    wloop.run_until_drained()

    # --- gang-aware run ----------------------------------------------
    loop, cfg, bw = _gang_loop(seed)
    pods = [_dc.replace(p, scheduler_name=cfg.scheduler_name)
            for p in pods]
    for key in by_gang:
        by_gang[key] = [p for p in pods if gang_key_of(p) == key]
    wall, bound_at = _drain_tracking_gangs(loop, pods, gang_keys)
    fully_bound = [k for k in gang_keys
                   if all(loop.client.node_of(p.name)
                          for p in by_gang[k])]
    partial = [k for k in gang_keys
               if k not in fully_bound
               and any(loop.client.node_of(p.name) for p in by_gang[k])]
    gang_bw = [mean_intra_gang_bw(bw, _member_node_idx(loop, by_gang[k]))
               for k in fully_bound]
    lat_ms = [bound_at[k] * 1e3 for k in gang_keys if k in bound_at]

    # --- independent baseline: annotations stripped ------------------
    # node_name must be cleared too: the fake apiserver binds by
    # mutating the SHARED Pod object, so after the gang run the
    # originals already carry their placement.
    base_pods = [_dc.replace(p, pod_group="", gang_min_member=0,
                             gang_timeout_s=0.0, node_name="")
                 for p in pods]
    bloop, _, _ = _gang_loop(seed)
    bwall = _drain(bloop, base_pods)
    base_bw = []
    for k in fully_bound:
        names = {p.name for p in by_gang[k]}
        members = [p for p in base_pods if p.name in names]
        if all(bloop.client.node_of(p.name) for p in members):
            base_bw.append(
                mean_intra_gang_bw(bw, _member_node_idx(bloop, members)))
    mean_gang = float(np.mean(gang_bw)) if gang_bw else 0.0
    mean_base = float(np.mean(base_bw)) if base_bw else 0.0

    # --- gate overhead on a gang-free workload -----------------------
    # Both loops are warmed with an untimed wave first so the gated/
    # ungated walls compare scheduling, not XLA compilation (the two
    # cfgs are distinct jit cache keys).
    over = generate_workload(
        WorkloadSpec(num_pods=overhead_pods, seed=seed + 5),
        scheduler_name=cfg.scheduler_name)
    walls = {}
    for label, enable in (("gated", True), ("ungated", False)):
        oloop, ocfg, _ = _gang_loop(seed + 9, enable=enable)
        warm = generate_workload(
            WorkloadSpec(num_pods=2 * batch, seed=seed + 6),
            scheduler_name=ocfg.scheduler_name)
        oloop.client.add_pods(warm)
        oloop.run_until_drained()
        before = oloop.scheduled
        w = _drain(oloop, [_dc.replace(p, name=f"o-{p.name}")
                           for p in over])
        walls[label] = (oloop.scheduled - before) / w if w else 0.0
    overhead_ratio = (round(walls["gated"] / walls["ungated"], 4)
                      if walls["ungated"] else 0.0)

    metrics = {
        "num_nodes": num_nodes,
        "gangs_submitted": len(gang_keys),
        "gangs_fully_bound": len(fully_bound),
        "gangs_partially_bound": len(partial),  # MUST stay 0
        "gang_members_total": sum(len(v) for v in by_gang.values()),
        "filler_pods": filler_pods,
        "gang_latency_p50_ms": (round(float(np.percentile(lat_ms, 50)), 2)
                                if lat_ms else 0.0),
        "gang_latency_p99_ms": (round(float(np.percentile(lat_ms, 99)), 2)
                                if lat_ms else 0.0),
        "mean_intra_gang_bw_gbps": round(mean_gang / 1e9, 4),
        "baseline_intra_gang_bw_gbps": round(mean_base / 1e9, 4),
        "intra_gang_bw_gain": (round(mean_gang / mean_base, 4)
                               if mean_base else 0.0),
        "gang_bw_strictly_higher": bool(mean_gang > mean_base),
        "pods_per_sec": round(loop.scheduled / wall, 1) if wall else 0.0,
        "baseline_pods_per_sec": (round(bloop.scheduled / bwall, 1)
                                  if bwall else 0.0),
        "gate_overhead_pods_per_sec": {
            k: round(v, 1) for k, v in walls.items()},
        "gate_overhead_ratio": overhead_ratio,  # >= 0.9 required
        "bench_env": bench_env(),
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "gang_scheduling.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(metrics, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("gang", metrics, artifacts)


def run_topology_config(out_dir: str | None = None,
                        num_nodes: int = 1024,
                        probe_budget: int = 64,
                        cycles: int = 280,
                        num_gangs: int = 16,
                        gang_members: int = 8,
                        seed: int = 0) -> SuiteResult:
    """Learned-topology leg (netmodel/): can the coordinate-embedding +
    low-rank bandwidth model, fed only a probe budget covering a few
    percent of the pair space, recover enough structure that gang
    placement on the BLENDED matrices approaches placement on the
    ground truth?

    Three placements of the same gang workload, all judged against the
    ground-truth bandwidth matrix:

    - sparse  — model disabled; scoring sees only the raw probe
      staging matrices (coverage < 5% of pairs, everything else 0);
    - blended — model enabled; unprobed pairs filled with
      confidence-weighted predictions;
    - oracle  — scoring sees the full ground-truth matrices.

    The reported bar is ``gain_ratio = (blended - sparse) /
    (oracle - sparse)``: the fraction of the oracle's bandwidth gain
    the learned model recovers.  Target >= 0.8 with probes covering
    < 5% of pairs.
    """
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
        generate_gang_workload,
    )
    from kubernetesnetawarescheduler_tpu.core import assign as assign_lib
    from kubernetesnetawarescheduler_tpu.core.encode import Encoder
    from kubernetesnetawarescheduler_tpu.core.gang import (
        gang_key_of,
        mean_intra_gang_bw,
        place_gang,
    )
    from kubernetesnetawarescheduler_tpu.core.score import static_node_scores
    from kubernetesnetawarescheduler_tpu.core.state import commit_assignments
    from kubernetesnetawarescheduler_tpu.ingest.probe import (
        FakeProber,
        ProbeOrchestrator,
    )
    from kubernetesnetawarescheduler_tpu.netmodel import (
        EIGProbePlanner,
        TopologyModel,
    )

    cfg = SchedulerConfig(
        max_nodes=_round_up(num_nodes, 128),
        max_pods=max(16, gang_members),
        max_peers=4,
        weights=BW_LAT,
        enable_netmodel=True,
        # ~10k Adam steps across the probe horizon: the inverse-sqrt
        # lr decay needs that depth to pass its noise floor (2k steps
        # leave same-rack ranking at ~0.92; 10k reaches ~0.99), and a
        # step costs well under a millisecond on one CPU core.
        netmodel_steps=36,
    )
    cluster, lat, bw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed))
    nodes = cluster.list_nodes()
    names = [n.name for n in nodes]
    enc = Encoder(cfg)
    for node in nodes:
        enc.upsert_node(node)
    feed_metrics(cluster, enc, np.random.default_rng(seed + 1))

    model = TopologyModel(cfg, seed=seed)
    enc.attach_netmodel(model)
    planner = EIGProbePlanner(
        model, explore_frac=cfg.netmodel_explore_frac, seed=seed)
    prober = FakeProber(names, lat, bw, noise=0.02, seed=seed)
    orch = ProbeOrchestrator(enc, prober, names,
                             planner=planner, model=model)
    for _ in range(cycles):
        orch.run_cycle(budget=probe_budget)
        orch.advance_clock(60.0)
    stale = orch.staleness()

    # Gang workload shared by all three placements.
    pods = generate_gang_workload(
        num_gangs=num_gangs, member_counts=(gang_members,),
        filler_pods=0, seed=seed)
    by_gang: dict[str, list[Pod]] = {}
    for p in pods:
        key = gang_key_of(p)
        if key:
            by_gang.setdefault(key, []).append(p)
    gang_keys = sorted(by_gang)

    def _eval(state) -> float:
        """Place every gang against ``state``; judge against truth."""
        static = static_node_scores(state, cfg)
        st, vals = state, []
        for key in gang_keys:
            members = by_gang[key]
            batch = enc.encode_pods(members, lambda n: "")
            a = place_gang(st, batch, cfg, static,
                           assign_lib.assign_parallel, len(members))
            st = commit_assignments(st, batch, jnp.asarray(a))
            vals.append(mean_intra_gang_bw(
                bw, np.asarray(a[:len(members)], np.int64)))
        return float(np.mean(vals)) if vals else 0.0

    blended_state = enc.snapshot()
    model.enabled = False
    enc.touch_net()
    sparse_state = enc.snapshot()
    model.enabled = True
    n_pad = cfg.max_nodes
    lat_pad = np.zeros((n_pad, n_pad), np.float32)
    bw_pad = np.zeros((n_pad, n_pad), np.float32)
    lat_pad[:num_nodes, :num_nodes] = lat
    bw_pad[:num_nodes, :num_nodes] = bw
    oracle_state = sparse_state.replace(
        lat=jnp.asarray(lat_pad), bw=jnp.asarray(bw_pad))

    sparse_bw = _eval(sparse_state)
    blended_bw = _eval(blended_state)
    oracle_bw = _eval(oracle_state)
    denom = oracle_bw - sparse_bw
    gain_ratio = ((blended_bw - sparse_bw) / denom) if denom > 0 else 1.0

    resid_p50, resid_p99 = model.residual_quantiles()

    def _f(x: float) -> float | None:
        return float(x) if np.isfinite(x) else None

    coverage = float(stale["coverage_fraction"])
    doc = {
        "metric": "topology_model",
        "value": round(float(gain_ratio), 6),
        "unit": "blended_gain_fraction_of_oracle",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "probe_budget": probe_budget,
            "cycles": cycles,
            "num_gangs": num_gangs,
            "gang_members": gang_members,
            "pairs_total": int(stale["total_pairs"]),
            "pairs_probed": int(stale["tracked_pairs"]),
            "coverage_fraction": coverage,
            "coverage_under_5pct": bool(coverage < 0.05),
            "oracle_bw_gbps": oracle_bw / 1e9,
            "sparse_bw_gbps": sparse_bw / 1e9,
            "blended_bw_gbps": blended_bw / 1e9,
            "gain_ratio": float(gain_ratio),
            "gain_target_met": bool(gain_ratio >= 0.8),
            "model_dim": cfg.netmodel_dim,
            "model_rank": cfg.netmodel_rank,
            "sgd_steps_total": model.steps_total,
            "residual_p50": _f(resid_p50),
            "residual_p99": _f(resid_p99),
            "planner_entropy_bits": float(planner.last_entropy_bits),
            "bench_env": bench_env(),
        },
    }
    artifacts = []
    if out_dir:
        path = os.path.join(out_dir, "topology.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2)
        artifacts.append(path)
    return SuiteResult("topology", doc, artifacts)


def run_integrity_config(out_dir: str | None = None,
                         num_nodes: int = 512,
                         num_pods: int = 512, batch: int = 32,
                         seed: int = 0) -> SuiteResult:
    """State-integrity leg (ISSUE 10): what does the anti-entropy
    audit cost, and does self-healing actually heal?

    Three proofs in one artifact:

    - **overhead** — the same workload drains twice from identical
      seeds, auditor off then auditor on (one ``audit_once`` per
      serving cycle, so the audit-cost sample is dense);
      ``overhead_fraction`` = median audit wall time / the default
      background audit interval — the fraction of serving capacity
      the anti-entropy daemon consumes at its production cadence, bar
      < 5%.  ``audit_per_cycle_fraction`` reports the stress ratio
      (audit p50 / cycle p50): what auditing EVERY cycle would cost.
    - **bit-identity** — both drains must produce byte-for-byte the
      same pod->node bindings: a passing audit only re-runs the flush
      the next cycle would have done anyway.
    - **fault matrix** — every runtime state-fault class
      (core/state_chaos.py) injected against the audited loop must be
      detected within one audit and repaired bit-identically
      (``unrepaired_drift`` == 0).
    """
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.core.integrity import (
        IntegrityAuditor,
    )
    from kubernetesnetawarescheduler_tpu.core.state_chaos import (
        run_state_fault_matrix,
    )

    def _drain_timed(loop, pods, auditor=None):
        # Batch-sized arrival waves, not one bulk add: a single add
        # would drain as ONE burst cycle and leave the percentile with
        # one sample.  One audit per cycle rides between waves.
        cycle_ms = []
        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            t0 = time.perf_counter()
            loop.run_once()
            cycle_ms.append((time.perf_counter() - t0) * 1e3)
            if auditor is not None:
                auditor.audit_once()
        while len(loop.queue) or loop._pipe_inflight is not None:
            t0 = time.perf_counter()
            loop.run_once()
            cycle_ms.append((time.perf_counter() - t0) * 1e3)
            if auditor is not None:
                auditor.audit_once()
        loop.flush_binds()
        loop.stop_bind_worker()
        return cycle_ms

    _warm_like(num_nodes, seed, BW_LAT, batch=batch, queue=num_pods)

    def _workload(cfg):
        return generate_workload(
            WorkloadSpec(num_pods=num_pods, seed=seed + 5,
                         services=8, peer_fraction=0.3),
            scheduler_name=cfg.scheduler_name)

    # Leg A: auditor off.
    loop_a, cfg_a = _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                               queue=num_pods)
    def _placements(loop):
        return sorted((b.namespace, b.pod_name, b.node_name)
                      for b in loop.client.bindings)

    cycles_a = _drain_timed(loop_a, _workload(cfg_a))
    bindings_a = _placements(loop_a)

    # Leg B: identical seeds, one audit per cycle.
    loop_b, cfg_b = _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                               queue=num_pods)
    auditor = IntegrityAuditor(loop_b.encoder, loop_b)
    loop_b.integrity = auditor
    # Warm the digest kernels (jit compile) outside the measured
    # window, then discard the warmup sample — otherwise one
    # compile-laden audit dominates the overhead ratio.
    auditor.audit_once()
    auditor.audit_ms.clear()
    cycles_b = _drain_timed(loop_b, _workload(cfg_b), auditor=auditor)
    bindings_b = _placements(loop_b)

    bit_identical = bindings_a == bindings_b
    audit_ms = list(auditor.audit_ms)
    p50_cycle = float(np.percentile(cycles_b, 50)) if cycles_b else 0.0
    p50_audit = float(np.median(audit_ms)) if audit_ms else 0.0
    # The auditor is a background daemon at ``interval_s`` cadence (the
    # IntegrityAuditor default — serve.py --audit-interval), NOT a
    # per-cycle stage: its full-state re-digest is fundamental (the
    # auditor must not trust the dirty tracking it is auditing, so
    # there is no incremental shortcut) and costs O(state) per pass.
    # Overhead on serving is therefore the fraction of wall time the
    # audit consumes at that cadence.  The per-cycle ratio is also
    # reported (``audit_per_cycle_fraction``) as the stress number —
    # what auditing EVERY cycle would cost.
    interval_s = IntegrityAuditor(loop_b.encoder).interval_s
    overhead = p50_audit / (interval_s * 1e3)
    per_cycle = (p50_audit / p50_cycle) if p50_cycle else 0.0

    # Fault matrix on the already-audited loop: every runtime class
    # detected within one audit, repaired back to digest equality.
    matrix = run_state_fault_matrix(loop_b.encoder, auditor,
                                    seed=seed + 6)
    all_detected = all(r["detected"] for r in matrix.values())
    unrepaired = sum(1 for r in matrix.values() if not r["repaired"])

    doc = {
        "metric": "state_integrity",
        "value": round(float(overhead), 6),
        "unit": "audit_fraction_of_serving_at_default_cadence",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "num_pods": num_pods,
            "batch": batch,
            "audit_enabled": True,
            "audits": auditor.audits_total,
            "audit_ms_p50": p50_audit,
            "audit_ms_p99": (float(np.percentile(audit_ms, 99))
                             if audit_ms else 0.0),
            "cycle_ms_p50_unaudited": (
                float(np.percentile(cycles_a, 50)) if cycles_a
                else 0.0),
            "cycle_ms_p50": p50_cycle,
            "audit_interval_s": float(interval_s),
            "overhead_fraction": float(overhead),
            "audit_per_cycle_fraction": float(per_cycle),
            "overhead_under_5pct": bool(overhead < 0.05),
            "clean_run_bit_identical": bool(bit_identical),
            "bindings": len(bindings_b),
            "fault_matrix": {
                k: {kk: vv for kk, vv in r.items()
                    if kk != "descriptor"}
                for k, r in matrix.items()},
            "all_faults_detected": bool(all_detected),
            "unrepaired_drift": int(unrepaired),
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "integrity.json", doc, artifacts)
    return SuiteResult("integrity", doc, artifacts)


def run_quality_config(out_dir: str | None = None,
                       num_nodes: int = 512,
                       num_pods: int = 512, batch: int = 32,
                       seed: int = 0,
                       drift_sigma: float = 0.3) -> SuiteResult:
    """Outcome-observability leg (ISSUE 11): what does watching
    placement quality cost, and what does it measure?

    Three proofs in one artifact:

    - **overhead** — the same workload drains twice from identical
      seeds, observation off then on (``note_commit`` riding every
      commit, one ``harvest`` per wave); ``overhead_fraction`` is the
      serving-cycle p50 inflation, bar < 2%.  Harvest cost (a
      maintain-cadence job, not a serving stage) is reported
      separately, like the integrity leg's audit_ms.
    - **bit-identity** — both drains must produce byte-for-byte the
      same pod->node bindings: ``note_commit`` only READS state and
      ``harvest`` runs off the hot path, so observation must not move
      a single placement.
    - **calibration under drift** — a third drained wave commits
      against the pre-drift matrices, then the staging network is
      perturbed (symmetric lognormal noise, ``drift_sigma``) before
      its harvest: the regret and bw-residual distributions must WAKE
      UP (nonzero), proving the join measures prediction error rather
      than echoing the inputs.
    """
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.obs.quality import (
        QualityObserver,
        _Pending,
        _round_pow2,
    )

    def _drain_timed(loop, pods, observer=None, harvest_ms=None):
        # Batch-sized arrival waves (same shape as the integrity leg);
        # one harvest per wave keeps the pending set wave-sized and
        # samples the maintain-cadence cost densely.
        cycle_ms = []

        def _tick():
            t0 = time.perf_counter()
            loop.run_once()
            cycle_ms.append((time.perf_counter() - t0) * 1e3)
            if observer is not None:
                t1 = time.perf_counter()
                observer.harvest(loop.encoder)
                harvest_ms.append((time.perf_counter() - t1) * 1e3)

        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            _tick()
        while len(loop.queue) or loop._pipe_inflight is not None:
            _tick()
        loop.flush_binds()
        loop.stop_bind_worker()
        return cycle_ms

    _warm_like(num_nodes, seed, BW_LAT, batch=batch, queue=num_pods)

    def _workload(cfg):
        return generate_workload(
            WorkloadSpec(num_pods=num_pods, seed=seed + 5,
                         services=8, peer_fraction=0.3),
            scheduler_name=cfg.scheduler_name)

    def _placements(loop):
        return sorted((b.namespace, b.pod_name, b.node_name)
                      for b in loop.client.bindings)

    # Leg A: observation off.
    loop_a, cfg_a = _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                               queue=num_pods)
    cycles_a = _drain_timed(loop_a, _workload(cfg_a))
    bindings_a = _placements(loop_a)

    # Leg B: identical seeds, observer attached DIRECTLY (same cfg
    # object as leg A's shape — flipping enable_quality_obs in cfg
    # would change the jit static arg and bill a recompile as
    # observation overhead).
    loop_b, cfg_b = _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                               queue=num_pods)
    observer = QualityObserver(cfg_b)
    loop_b.quality = observer
    # Warm the evaluator for every pow2 pad the waves can produce —
    # synthetic pendings through the module-level jit cache, outside
    # the measured window.
    warm = QualityObserver(cfg_b)
    size = 8
    while True:
        for i in range(size):
            uid = f"warm-{size}-{i}"
            warm._pending[uid] = _Pending(
                uid=uid, node="warm", node_idx=0, cycle_id=0,
                t_commit=0.0, peer_idx=(0,), peer_traffic=(1.0,),
                pred_lat_ms=(0.1,), pred_bw_bps=(1e9,),
                score_pred=None)
        warm.harvest(loop_b.encoder)
        if size >= _round_pow2(batch):
            break
        size *= 2
    harvest_ms: list[float] = []
    cycles_b = _drain_timed(loop_b, _workload(cfg_b),
                            observer=observer, harvest_ms=harvest_ms)
    bindings_b = _placements(loop_b)
    bit_identical = bindings_a == bindings_b
    clean = observer.summary()

    p50_a = float(np.percentile(cycles_a, 50)) if cycles_a else 0.0
    p50_b = float(np.percentile(cycles_b, 50)) if cycles_b else 0.0
    overhead = max(0.0, p50_b / p50_a - 1.0) if p50_a else 0.0
    p50_harvest = float(np.median(harvest_ms)) if harvest_ms else 0.0

    # Leg C (drift): commit a fresh wave against today's matrices,
    # then perturb the staging network BEFORE its harvest — the
    # prediction/observation gap the join exists to measure.
    drift_pods = generate_workload(
        WorkloadSpec(num_pods=min(num_pods, 256), seed=seed + 11,
                     services=8, peer_fraction=0.6),
        scheduler_name=cfg_b.scheduler_name)
    loop_b.client.add_pods(drift_pods)
    loop_b.run_until_drained()
    enc = loop_b.encoder
    with enc._lock:
        lat0 = np.array(enc._lat, dtype=np.float64)
        bw0 = np.array(enc._bw, dtype=np.float64)
    rng = np.random.default_rng(seed + 12)
    noise = rng.lognormal(mean=0.0, sigma=drift_sigma,
                          size=lat0.shape)
    noise = (noise + noise.T) / 2.0     # links drift symmetrically
    enc.set_network(lat0 * noise, bw0 / noise)
    observer.harvest(enc)
    drifted = observer.summary()

    regret_p99 = float(drifted["regret_p99"])
    cal_samples = int(drifted["calibration_samples"])
    doc = {
        "metric": "placement_quality",
        "value": round(float(overhead), 6),
        "unit": "observation_overhead_fraction_of_cycle_p50",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "num_pods": num_pods,
            "batch": batch,
            "observation_enabled": True,
            "cycle_ms_p50_off": p50_a,
            "cycle_ms_p50_on": p50_b,
            "overhead_fraction": float(overhead),
            "overhead_under_2pct": bool(overhead < 0.02),
            "bit_identical": bool(bit_identical),
            "bindings": len(bindings_b),
            "harvest_ms_p50": p50_harvest,
            "harvest_ms_p99": (float(np.percentile(harvest_ms, 99))
                               if harvest_ms else 0.0),
            "harvests": len(harvest_ms),
            "commits_noted": int(drifted["noted_total"]),
            "no_peer_skipped": int(drifted["no_peer_total"]),
            "outcomes": int(drifted["harvested_total"]),
            "calibration_samples": cal_samples,
            # Clean-leg distributions: commits harvested against the
            # SAME matrices they were scored on — regret here is the
            # placement's real suboptimality (conflict fallbacks,
            # capacity), not prediction error.
            "regret_p50_clean": float(clean["regret_p50"]),
            "regret_p99_clean": float(clean["regret_p99"]),
            "bw_residual_p99_clean":
                float(clean["bw_residual_log1p_p99"]),
            # Post-drift distributions: the join must WAKE UP.
            "drift_sigma": float(drift_sigma),
            "regret_p50": float(drifted["regret_p50"]),
            "regret_p99": regret_p99,
            "bw_residual_p50":
                float(drifted["bw_residual_log1p_p50"]),
            "bw_residual_p99":
                float(drifted["bw_residual_log1p_p99"]),
            "drift_detected": bool(
                drifted["bw_residual_log1p_p99"]
                > clean["bw_residual_log1p_p99"]),
            "ring_depth": int(drifted["ring_depth"]),
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "quality.json", doc, artifacts)
    return SuiteResult("quality", doc, artifacts)


def run_rebalance_config(out_dir: str | None = None,
                         num_nodes: int = 2048,
                         num_pods: int = 512, batch: int = 64,
                         seed: int = 0,
                         drift_nodes: int = 64,
                         drift_factor: float = 50.0,
                         rounds: int = 8) -> SuiteResult:
    """Continuous-rebalancing leg (ISSUE 12): when links degrade under
    a placed workload, how much of the lost realized bandwidth does the
    budgeted descheduler claw back — and what does it cost in
    disruption?

    Four placements of ONE workload, all measured on the same
    ground-truth DRIFTED matrices (traffic-weighted realized peer
    bandwidth over the final pod->node map):

    - **no_rebalance** — drains against the clean network, then the
      links under the busiest ``drift_nodes`` nodes degrade
      (``lat * drift_factor``, ``bw / drift_factor``) and nothing
      acts.  This is the pre-r12 scheduler: placements frozen at
      yesterday's truth.
    - **no_drift control** — identical drain with the rebalancer
      attached at DEFAULT hysteresis knobs and ticked repeatedly:
      the placements must stay bit-identical and the move count ~0
      (healthy clusters carry structural net regret — the gain/age
      bars must hold it).
    - **rebalance** — same degradation, but serve.py's link-event feed
      is replayed into ``note_link_event`` and the rebalancer ticks
      under an explicit eviction budget; evicted pods re-place through
      the normal pipeline (pinned by the migration ledger).
    - **oracle** — a fresh loop schedules the workload with full
      knowledge of the drifted network: the re-place-everything
      reference.  NOT a strict upper bound on this metric: an
      in-place mover optimizes the pure net term over the complete
      peer map, while fresh scheduling pays arrival-order blindness
      and spreads for balance — ``recovered_frac`` can exceed 1.

    Headline: ``recovered_frac = (rebalance - no_rebalance) /
    (oracle - no_rebalance)``, bar >= 0.6, with
    ``evictions_per_pod_hour`` reported beside it (Rule 12 checks it
    stays under the configured budget) and ``half_moved_gangs == 0``.
    """
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.core.rebalance import Rebalancer

    rb_knobs = dict(
        enable_rebalance=True,
        rebalance_interval_s=1e-4,      # bench ticks explicitly
        rebalance_max_moves_per_cycle=64,
        rebalance_evictions_per_hour=256.0,
        rebalance_move_timeout_s=120.0,
        # min_gain / min_age / cooldown stay at DEFAULTS: the no-drift
        # control proves the hysteresis holds, the drift leg moves on
        # link-event triggers (which bypass the gain/age bars by
        # design, not by knob relaxation).
    )

    def _mk():
        return _make_loop(num_nodes, seed, BW_LAT, batch=batch,
                          queue=num_pods)

    def _attach(loop, cfg):
        # The rebalancer gets its OWN cfg copy (same trick the quality
        # leg uses): flipping enable_rebalance on loop.cfg would change
        # the jit static arg and bill a recompile against legs that
        # must stay comparable.
        rb_cfg = dataclasses.replace(cfg, **rb_knobs)
        rb = Rebalancer(rb_cfg, loop.encoder, loop.client)
        loop.rebalance = rb
        return rb, rb_cfg

    def _workload(cfg):
        return generate_workload(
            WorkloadSpec(num_pods=num_pods, seed=seed + 5,
                         services=8, peer_fraction=0.6),
            scheduler_name=cfg.scheduler_name)

    def _drain(loop, pods):
        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            loop.run_once()
        loop.run_until_drained()
        loop.flush_binds()

    def _placements(loop) -> dict[str, str]:
        # Bindings ACCUMULATE (a moved pod re-binds); the placement is
        # the LAST binding per pod.
        out: dict[str, str] = {}
        for b in loop.client.bindings:
            out[b.pod_name] = b.node_name
        return out

    _warm_like(num_nodes, seed, BW_LAT, batch=batch, queue=num_pods)

    # ---- leg A: no rebalance (the pre-r12 scheduler) --------------
    loop_a, cfg_a = _mk()
    pods = _workload(cfg_a)
    _drain(loop_a, pods)
    placed_a = _placements(loop_a)
    enc_a = loop_a.encoder
    with enc_a._lock:
        lat0 = np.array(enc_a._lat, dtype=np.float64)
        bw0 = np.array(enc_a._bw, dtype=np.float64)

    # Ground-truth drift: degrade every link touching the busiest
    # drift_nodes nodes of leg A's placement (the worst case — the
    # degradation lands exactly where the traffic is).
    by_node: dict[str, int] = {}
    for node in placed_a.values():
        by_node[node] = by_node.get(node, 0) + 1
    hot = sorted(by_node, key=lambda n: (-by_node[n], n))[:drift_nodes]
    hot_idx = [enc_a.node_slot(n) for n in hot]
    lat_d, bw_d = lat0.copy(), bw0.copy()
    for i in hot_idx:
        lat_d[i, :] *= drift_factor
        lat_d[:, i] *= drift_factor
        bw_d[i, :] /= drift_factor
        bw_d[:, i] /= drift_factor
    np.fill_diagonal(lat_d, 0.0)
    loopback = float(bw0.max())

    def _realized_bw(placements: dict[str, str], enc) -> float:
        """Traffic-weighted realized peer bandwidth under the DRIFTED
        ground truth (loopback pinned to the matrix max for co-placed
        peers, the scorer's own convention)."""
        total = 0.0
        for pod in pods:
            if not pod.peers:
                continue
            ni = placements.get(pod.name)
            ii = enc.node_slot(ni) if ni else None
            if ii is None:
                continue
            for peer, w in pod.peers.items():
                nj = placements.get(peer)
                jj = enc.node_slot(nj) if nj else None
                if jj is None:
                    continue
                total += w * (loopback if ii == jj
                              else float(bw_d[ii, jj]))
        return total

    bw_a = _realized_bw(placed_a, enc_a)
    loop_a.stop_bind_worker()

    # ---- leg B: no-drift control (hysteresis must hold) -----------
    loop_b, cfg_b = _mk()
    rb_b, _ = _attach(loop_b, cfg_b)
    _drain(loop_b, _workload(cfg_b))
    for _ in range(3):
        rb_b._last_tick = 0.0
        rb_b.tick(loop_b)
        loop_b.run_until_drained()
        loop_b.flush_binds()
    placed_b = _placements(loop_b)
    no_drift_moves = rb_b.moves_total
    bit_identical = placed_a == placed_b
    loop_b.stop_bind_worker()

    # ---- leg C: drift + rebalance ---------------------------------
    loop_c, cfg_c = _mk()
    rb_c, rb_cfg_c = _attach(loop_c, cfg_c)
    _drain(loop_c, _workload(cfg_c))
    enc_c = loop_c.encoder
    # The links degrade: staging learns the drifted truth (what the
    # ingest path's set_network does when probes report) ...
    enc_c.set_network(lat_d.astype(np.float64),
                      bw_d.astype(np.float64))
    scan_ms: list[float] = []
    for _ in range(rounds):
        # ... and serve.py's quarantine/degradation watch feeds the
        # structured link Events back in each cycle the streak holds.
        for n in hot:
            rb_c.note_link_event(n, "", "degraded", streak=1)
        rb_c._last_tick = 0.0
        t0 = time.perf_counter()
        moved = rb_c.tick(loop_c)
        scan_ms.append((time.perf_counter() - t0) * 1e3)
        loop_c.run_until_drained()
        loop_c.flush_binds()
        if moved == 0 and not rb_c._inflight:
            break
    rb_c._last_tick = 0.0
    rb_c.tick(loop_c)           # settle the final wave
    placed_c = _placements(loop_c)
    bw_c = _realized_bw(placed_c, enc_c)
    rb_summary = rb_c.summary()
    evictions_per_pod_hour = rb_c.disruption_per_pod_hour(num_pods)
    budget_per_pod_hour = (rb_cfg_c.rebalance_evictions_per_hour
                           / max(1, num_pods))
    loop_c.stop_bind_worker()

    # ---- oracle: full re-place under the drifted truth ------------
    loop_o, cfg_o = _mk()
    loop_o.encoder.set_network(lat_d.astype(np.float64),
                               bw_d.astype(np.float64))
    _drain(loop_o, _workload(cfg_o))
    bw_o = _realized_bw(_placements(loop_o), loop_o.encoder)
    loop_o.stop_bind_worker()

    oracle_gain = bw_o - bw_a
    recovered = ((bw_c - bw_a) / oracle_gain
                 if oracle_gain > 0 else 1.0)

    doc = {
        "metric": "rebalance_recovery",
        "value": round(float(recovered), 6),
        "unit": "fraction_of_oracle_bandwidth_gain_recovered",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "num_pods": num_pods,
            "batch": batch,
            "drift_nodes": drift_nodes,
            "drift_factor": float(drift_factor),
            "rebalance_enabled": True,
            "recovered_frac": float(recovered),
            "no_rebalance_bw": float(bw_a),
            "rebalance_bw": float(bw_c),
            "oracle_bw": float(bw_o),
            "oracle_gain": float(oracle_gain),
            "moves": int(rb_summary["moves_total"]),
            "moves_completed": int(rb_summary["moves_completed"]),
            "moves_reverted": int(rb_summary["moves_reverted"]),
            "pods_evicted": int(rb_summary["pods_evicted_total"]),
            "half_moved_gangs": int(rb_summary["half_moved_gangs"]),
            "evictions_per_pod_hour": float(evictions_per_pod_hour),
            "budget_per_pod_hour": float(budget_per_pod_hour),
            "no_drift_moves": int(no_drift_moves),
            "no_drift_bit_identical": bool(bit_identical),
            "skipped_gain": int(rb_summary["skipped_gain"]),
            "skipped_age": int(rb_summary["skipped_age"]),
            "skipped_cooldown": int(rb_summary["skipped_cooldown"]),
            "skipped_budget": int(rb_summary["skipped_budget"]),
            "skipped_disruption":
                int(rb_summary["skipped_disruption"]),
            "triggers_link": int(rb_summary["triggers_link"]),
            "scan_ms_p50": (float(np.percentile(scan_ms, 50))
                            if scan_ms else 0.0),
            "scan_ms_max": (float(max(scan_ms)) if scan_ms else 0.0),
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "rebalance.json", doc, artifacts)
    return SuiteResult("rebalance", doc, artifacts)


def run_reshape_config(out_dir: str | None = None,
                       num_nodes: int = 64,
                       num_gangs: int = 10, gang_size: int = 8,
                       filler_pods: int = 48, batch: int = 96,
                       seed: int = 0, zones: int = 4,
                       outage_zone: int = 0,
                       drift_factor: float = 40.0,
                       rounds: int = 12) -> SuiteResult:
    """Elastic gang reshaping leg (ISSUE 19): a ZONAL OUTAGE strands
    placed gangs behind catastrophically degraded links — how much of
    the lost realized bandwidth does shape-aware degrade-and-recover
    claw back, at what disruption cost, with ZERO half-shaped gangs?

    Every gang declares the elastic family ``"S,S/2:0.5"`` (full shape
    preferred, half shape at half desirability).  Four placements of
    ONE workload (gangs whose members exchange ring traffic, plus
    plain filler pods that keep the healthy zones under capacity
    pressure), all measured on the same post-outage ground truth:

    The outage itself is the kubelet-real combination: the zone's
    nodes go NotReady (cordoned — running pods keep their bindings,
    the feasibility mask drops the nodes from every future placement)
    and every link touching them degrades (``lat * drift_factor``,
    ``bw / drift_factor``).

    - **no_reshape control** — drains against the clean network, then
      the outage lands and nothing acts: the pre-r17 all-or-nothing
      scheduler, gangs frozen behind the partition with members
      stranded on dead nodes.
    - **no-outage control** — reshaping fully enabled, network left
      healthy, the rebalancer ticked repeatedly: placements must stay
      identical to the control leg and the reshape count 0 (a healthy
      full-shape gang is invisible to the reshape pass).
    - **reshape treatment** — same outage; serve.py's link-event feed
      marks the zone's nodes hot and the reshape pass evicts degraded
      gangs as units through the reshape ledger; the shape-aware gang
      path re-places each at the best feasible realization (full
      where the surviving zones have room, half where they don't).
    - **oracle** — a fresh shape-aware loop schedules the workload
      with full knowledge of the degraded network.

    Headline: ``recovered_frac = (treatment - control) / (oracle -
    control)``, bar > 0.5, with ``half_shaped_gangs == 0`` and
    ``evictions_per_pod_hour`` within budget (bench_check Rule 17).
    """
    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.core.gang import (
        parse_gang_shapes,
    )
    from kubernetesnetawarescheduler_tpu.core.rebalance import Rebalancer

    num_pods = num_gangs * gang_size + filler_pods
    queue = max(300, 2 * num_pods)
    shapes = parse_gang_shapes(
        f"{gang_size},{max(1, gang_size // 2)}:0.5")

    def _mk(reshape: bool):
        cfg = SchedulerConfig(
            max_nodes=_round_up(num_nodes, 128), max_pods=batch,
            max_peers=4, weights=BW_LAT, queue_capacity=queue,
            # Static to the jitted assigners — set from construction,
            # never flipped on a live loop.
            enable_gang_reshaping=reshape,
        )
        cluster, lat, bw = build_fake_cluster(
            ClusterSpec(num_nodes=num_nodes, seed=seed, zones=zones))
        loop = SchedulerLoop(cluster, cfg, method="parallel")
        loop.encoder.set_network(lat, bw)
        feed_metrics(cluster, loop.encoder,
                     np.random.default_rng(seed + 1))
        return loop, cfg, cluster

    def _attach(loop, cfg, reshape: bool):
        # Move scan OFF in every leg: the single-pod/move path is
        # r12's subject; this leg isolates the reshape contribution.
        rb_cfg = dataclasses.replace(
            cfg,
            enable_rebalance=True,
            rebalance_interval_s=1e-4,      # bench ticks explicitly
            rebalance_max_moves_per_cycle=0,
            rebalance_evictions_per_hour=256.0,
            rebalance_move_timeout_s=120.0,
            enable_gang_reshaping=reshape,
            reshape_max_per_cycle=4,
        )
        rb = Rebalancer(rb_cfg, loop.encoder, loop.client)
        loop.rebalance = rb
        return rb, rb_cfg

    def _workload(cfg) -> list[Pod]:
        pods: list[Pod] = []
        for g in range(num_gangs):
            group = f"rg{g:03d}"
            for m in range(gang_size):
                peers = {f"{group}-w{(m + 1) % gang_size:02d}": 10.0}
                pods.append(Pod(
                    name=f"{group}-w{m:02d}",
                    scheduler_name=cfg.scheduler_name,
                    requests={"cpu": 4.0, "mem": 8.0, "net_bw": 1.0},
                    peers=peers, pod_group=group,
                    gang_min_member=gang_size, priority=5.0,
                    # Self-anti-affinity: one worker per host (the
                    # TPU-slice regime) — a ring that collapses onto
                    # one node is all loopback and blind to any
                    # outage.
                    group=group, anti_groups=frozenset({group}),
                    gang_shapes=shapes))
        filler = generate_workload(
            WorkloadSpec(num_pods=filler_pods, seed=seed + 5,
                         services=6, peer_fraction=0.0,
                         cpu_range=(1.0, 4.0), mem_range=(2.0, 8.0)),
            scheduler_name=cfg.scheduler_name)
        return pods + list(filler)

    def _drain(loop, pods):
        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            loop.run_once()
        loop.run_until_drained()
        loop.flush_binds()

    def _placements(loop) -> dict[str, str]:
        out: dict[str, str] = {}
        for b in loop.client.bindings:
            out[b.pod_name] = b.node_name
        return out

    zone_nodes = [f"node-{i:04d}" for i in range(num_nodes)
                  if i % zones == outage_zone % max(1, zones)]

    def _cordon(cluster):
        # Zone goes NotReady: the informer upserts the node with
        # unschedulable set, which drops it from every feasibility
        # mask while bound pods keep their usage (kubelet-real).
        for node in cluster.list_nodes():
            if node.name in zone_nodes:
                cluster.add_node(
                    dataclasses.replace(node, unschedulable=True))

    _warm_like(num_nodes, seed, BW_LAT, batch=batch, queue=queue)

    # ---- leg A: outage, no reshape (the pre-r17 scheduler) --------
    loop_a, cfg_a, cl_a = _mk(reshape=False)
    pods = _workload(cfg_a)
    _drain(loop_a, pods)
    placed_a = _placements(loop_a)
    enc_a = loop_a.encoder
    with enc_a._lock:
        lat0 = np.array(enc_a._lat, dtype=np.float64)
        bw0 = np.array(enc_a._bw, dtype=np.float64)
    zone_idx = [enc_a.node_slot(n) for n in zone_nodes]
    lat_d, bw_d = lat0.copy(), bw0.copy()
    for i in zone_idx:
        lat_d[i, :] *= drift_factor
        lat_d[:, i] *= drift_factor
        bw_d[i, :] /= drift_factor
        bw_d[:, i] /= drift_factor
    np.fill_diagonal(lat_d, 0.0)
    loopback = float(bw0.max())

    def _realized_bw(placements: dict[str, str], enc) -> float:
        total = 0.0
        for pod in pods:
            if not pod.peers:
                continue
            ni = placements.get(pod.name)
            ii = enc.node_slot(ni) if ni else None
            if ii is None:
                continue
            for peer, w in pod.peers.items():
                nj = placements.get(peer)
                jj = enc.node_slot(nj) if nj else None
                if jj is None:
                    continue
                total += w * (loopback if ii == jj
                              else float(bw_d[ii, jj]))
        return total

    _cordon(cl_a)               # the control sees the outage too —
    bw_a = _realized_bw(placed_a, enc_a)   # it just cannot act on it
    loop_a.stop_bind_worker()

    # ---- leg B: no-outage control (reshape pass must sleep) -------
    loop_b, cfg_b, _ = _mk(reshape=True)
    rb_b, _ = _attach(loop_b, cfg_b, reshape=True)
    _drain(loop_b, _workload(cfg_b))
    for _ in range(3):
        rb_b._last_tick = 0.0
        rb_b.tick(loop_b)
        loop_b.run_until_drained()
        loop_b.flush_binds()
    placed_b = _placements(loop_b)
    no_outage_reshapes = rb_b.reshapes_total
    no_outage_identical = placed_a == placed_b
    loop_b.stop_bind_worker()

    # ---- leg C: outage + reshape ----------------------------------
    loop_c, cfg_c, cl_c = _mk(reshape=True)
    rb_c, rb_cfg_c = _attach(loop_c, cfg_c, reshape=True)
    _drain(loop_c, _workload(cfg_c))
    enc_c = loop_c.encoder
    _cordon(cl_c)
    enc_c.set_network(lat_d.astype(np.float64),
                      bw_d.astype(np.float64))
    scan_ms: list[float] = []
    for _ in range(rounds):
        for n in zone_nodes:
            rb_c.note_link_event(n, "", "degraded", streak=1)
        rb_c._last_tick = 0.0
        t0 = time.perf_counter()
        moved = rb_c.tick(loop_c)
        scan_ms.append((time.perf_counter() - t0) * 1e3)
        loop_c.run_until_drained()
        loop_c.flush_binds()
        if (moved == 0 and not rb_c._inflight
                and not rb_c._inflight_reshapes):
            break
    rb_c._last_tick = 0.0
    rb_c.tick(loop_c)           # settle the final wave
    placed_c = _placements(loop_c)
    bw_c = _realized_bw(placed_c, enc_c)
    rb_summary = rb_c.summary()
    resh = rb_summary.get("reshape", {})
    evictions_per_pod_hour = rb_c.disruption_per_pod_hour(num_pods)
    budget_per_pod_hour = (rb_cfg_c.rebalance_evictions_per_hour
                           / max(1, num_pods))
    loop_c.stop_bind_worker()

    # ---- oracle: fresh shape-aware schedule under the outage ------
    loop_o, cfg_o, cl_o = _mk(reshape=True)
    _cordon(cl_o)
    loop_o.encoder.set_network(lat_d.astype(np.float64),
                               bw_d.astype(np.float64))
    _drain(loop_o, _workload(cfg_o))
    bw_o = _realized_bw(_placements(loop_o), loop_o.encoder)
    loop_o.stop_bind_worker()

    oracle_gain = bw_o - bw_a
    recovered = ((bw_c - bw_a) / oracle_gain
                 if oracle_gain > 0 else 1.0)

    doc = {
        "metric": "reshape_recovery",
        "value": round(float(recovered), 6),
        "unit": "fraction_of_oracle_bandwidth_gain_recovered",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "num_gangs": num_gangs,
            "gang_size": gang_size,
            "filler_pods": filler_pods,
            "zones": zones,
            "outage_zone": int(outage_zone),
            "zone_nodes": len(zone_nodes),
            "drift_factor": float(drift_factor),
            "recovered_frac": float(recovered),
            "no_reshape_bw": float(bw_a),
            "reshape_bw": float(bw_c),
            "oracle_bw": float(bw_o),
            "oracle_gain": float(oracle_gain),
            "reshape": {
                "enabled": True,
                "reshapes_total": int(resh.get("reshapes_total", 0)),
                "reshapes_completed":
                    int(resh.get("reshapes_completed", 0)),
                "reshapes_reverted":
                    int(resh.get("reshapes_reverted", 0)),
                "half_shaped_gangs":
                    int(resh.get("half_shaped_gangs", 0)),
                "shrinks": int(resh.get("shrinks", 0)),
                "regrows": int(resh.get("regrows", 0)),
                "retiles": int(resh.get("retiles", 0)),
                "skipped_gain": int(resh.get("skipped_gain", 0)),
                "skipped_budget": int(resh.get("skipped_budget", 0)),
                "recovered_frac": float(recovered),
                "evictions_per_pod_hour":
                    float(evictions_per_pod_hour),
                "budget_per_pod_hour": float(budget_per_pod_hour),
                "no_outage_reshapes": int(no_outage_reshapes),
                "no_outage_identical": bool(no_outage_identical),
            },
            "pods_evicted": int(rb_summary["pods_evicted_total"]),
            "half_moved_gangs": int(rb_summary["half_moved_gangs"]),
            "evictions_per_pod_hour": float(evictions_per_pod_hour),
            "budget_per_pod_hour": float(budget_per_pod_hour),
            "scan_ms_p50": (float(np.percentile(scan_ms, 50))
                            if scan_ms else 0.0),
            "scan_ms_max": (float(max(scan_ms)) if scan_ms else 0.0),
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "reshape.json", doc, artifacts)
    return SuiteResult("reshape", doc, artifacts)


def run_scenario_config(out_dir: str | None = None,
                        num_nodes: int = 256,
                        duration_s: float = 2900.0,
                        base_rate: float = 360.0,
                        batch: int = 256, seed: int = 0,
                        gang_fraction: float = 0.0005,
                        oracle_sample: int = 2048,
                        slo_budget_ms: float = 250.0,
                        keep_trace: bool = False) -> SuiteResult:
    """Trace-driven scenario campaign (ISSUE 14): generate a
    million-pod diurnal workload trace and stream it through the REAL
    SchedulerLoop — chaos proxy, link-degradation bursts, node churn,
    state faults, budgeted rebalancing and the quality observer all
    live — then publish the outcome scorecard.

    Unlike every other leg this one measures the SYSTEM over hours of
    virtual time, not one subsystem over one drain: the headline is
    streaming throughput (pods per wall second), and the evidence the
    Rule 13 gate wants rides in ``detail.scenario``-shaped fields —
    ``pods_streamed``, the full scorecard, ``half_moved_gangs == 0``
    and peak-RSS proof that memory stayed bounded while the trace
    streamed (default full shape: ~1.04M pods on a 256-node fleet at
    ~56% steady-state CPU occupancy, diurnal peaks to ~75% — sized so
    the cluster never saturates: a saturated campaign turns into an
    unschedulable retry storm that overflows the informer queue and
    trips the queue_dropped bar, measured at 192 nodes/410 pods/s).

    The trace itself is written to a TEMP dir (gzip) and deleted
    after the replay — it is multi-GB-scale raw and reproducible from
    (seed, spec) by construction, so committing it would be waste;
    ``keep_trace`` retains it for debugging.
    """
    import tempfile

    from kubernetesnetawarescheduler_tpu.scenario.generate import (
        ScenarioSpec,
        generate_trace,
    )
    from kubernetesnetawarescheduler_tpu.scenario.replay import (
        replay_trace,
    )
    from kubernetesnetawarescheduler_tpu.scenario.scorecard import (
        build_scorecard,
        check_scorecard,
    )

    spec = ScenarioSpec(
        seed=seed,
        duration_s=duration_s,
        base_rate=base_rate,
        diurnal_amplitude=0.3,
        day_s=max(duration_s / 4.0, 60.0),
        gang_fraction=gang_fraction,
        gang_sizes=(8,),
        longrun_fraction=0.003,
        serving_lifetime_s=12.0,
        batch_lifetime_s=6.0,
        gang_lifetime_s=10.0,
        lifetime_floor_s=2.0,
        link_burst_rate_per_s=0.01,
        link_burst_duration_s=15.0,
        node_churn_rate_per_s=0.002,
        node_down_duration_s=20.0,
        state_fault_rate_per_s=0.002,
        chaos_seed=seed + 17,
        cluster=ClusterSpec(
            num_nodes=num_nodes, seed=seed,
            node_classes=(
                NodeClassSpec("std", 0.5),
                NodeClassSpec("highmem", 0.25,
                              mem_range=(64.0, 256.0)),
                NodeClassSpec("edge", 0.25, cpu_range=(8.0, 32.0),
                              lat_scale=2.0, bw_scale=0.5),
            )),
    )

    tmp = tempfile.mkdtemp(prefix="scenario_trace_")
    trace_path = os.path.join(tmp, "trace.jsonl.gz")
    t0 = time.perf_counter()
    gen_stats = generate_trace(spec, trace_path)
    gen_wall = time.perf_counter() - t0
    trace_bytes = os.path.getsize(trace_path)

    sampler = UsageSampler(period_s=0.5)
    sampler.start()
    t0 = time.perf_counter()
    try:
        res = replay_trace(
            trace_path, batch=batch, oracle_sample=oracle_sample,
            slo_budget_ms=slo_budget_ms)
    finally:
        sampler.stop()
        if not keep_trace:
            try:
                os.remove(trace_path)
                os.rmdir(tmp)
            except OSError:
                pass
    replay_wall = time.perf_counter() - t0

    card = build_scorecard(res, evictions_per_hour_budget=512.0)
    problems = check_scorecard(card)
    peak_rss = int(max([res.peak_rss_bytes] + sampler.mem)
                   if sampler.mem else res.peak_rss_bytes)
    pods_per_sec = res.pods_streamed / max(replay_wall, 1e-9)
    half_moved = int(card["rebalance"]["half_moved_gangs"])
    inv = res.invariants or {}

    doc = {
        "metric": "scenario_campaign",
        "value": round(float(pods_per_sec), 3),
        "unit": "pods_per_wall_second",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "batch": batch,
            "duration_virtual_s": float(res.duration_virtual_s),
            "replay_wall_s": float(replay_wall),
            "gen_wall_s": float(gen_wall),
            "trace_bytes_gz": int(trace_bytes),
            "gen_stats": {k: int(v) for k, v in gen_stats.items()},
            "pods_streamed": int(res.pods_streamed),
            "pods_bound": int(res.pods_bound),
            "events_consumed": int(res.events_consumed),
            "queue_dropped": int(res.queue_dropped),
            "unschedulable_events": int(res.unschedulable),
            "scorecard": card,
            "scorecard_problems": problems,
            "half_moved_gangs": half_moved,
            "peak_rss_bytes": peak_rss,
            "rss_first_bytes": int(res.rss_samples[0]
                                   if res.rss_samples else 0),
            "rss_last_bytes": int(res.rss_samples[-1]
                                  if res.rss_samples else 0),
            "pods_double_bound": int(inv.get("pods_double_bound", 0)),
            "invariants": {k: int(v) for k, v in inv.items()},
            "cycle_p50_ms": float(res.cycle_ms.percentile(50.0)),
            "cycle_p99_ms": float(res.cycle_ms.percentile(99.0)),
            "spec": {
                "base_rate": float(base_rate),
                "gang_fraction": float(gang_fraction),
                "oracle_sample": int(oracle_sample),
                "slo_budget_ms": float(slo_budget_ms),
                "node_classes": [c.name for c in
                                 spec.cluster.node_classes],
            },
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "scenario.json", doc, artifacts)
    return SuiteResult("scenario", doc, artifacts)


def run_policy_config(out_dir: str | None = None,
                      num_nodes: int = 128,
                      num_pods: int = 256, batch: int = 32,
                      seed: int = 0,
                      duration_s: float = 60.0,
                      base_rate: float = 40.0,
                      oracle_sample: int = 256) -> SuiteResult:
    """Learned-scoring-policy leg (ISSUE 15): what does shadow
    scoring cost, and does the counterfactual promotion gate actually
    gate?

    Three proofs in one artifact:

    - **disabled bit-identity + shadow overhead** — the same workload
      drains twice from identical seeds, bare vs with the policy
      attached and shadow-scoring every wave (explain capture on in
      BOTH legs, so the comparison isolates the policy's own cost):
      placements must be byte-identical and the serving-cycle p50
      inflation must stay under the 2% bar.
    - **the gate refuses a seeded loser** — a network-blind candidate
      (peer terms zeroed) goes through the gate against the recorded
      decisions; the cheap records leg must catch the net regression
      before any replay is spent on it.
    - **the gate promotes a seeded winner, quantified vs oracle** —
      one seeded scenario trace (heterogeneous cluster with degraded
      edge links + live link-drift bursts) replays twice through the
      REAL loop, net-blind incumbent vs net-aware candidate; the
      candidate must win ``realized_bw_ratio_vs_oracle`` and the
      headline is the fraction of the incumbent→oracle bandwidth gap
      it recovers.
    """
    import dataclasses
    import tempfile

    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.obs.quality import (
        QualityObserver,
    )
    from kubernetesnetawarescheduler_tpu.policy import (
        PolicyDataset,
        ScoringPolicy,
        evaluate_candidate,
    )
    from kubernetesnetawarescheduler_tpu.scenario.generate import (
        ScenarioSpec,
        generate_trace,
    )
    from kubernetesnetawarescheduler_tpu.scenario.replay import (
        REPLAY_WEIGHTS,
    )

    def _cfg():
        return SchedulerConfig(
            max_nodes=_round_up(num_nodes, 128), max_pods=batch,
            max_peers=4, weights=BW_LAT,
            queue_capacity=max(300, num_pods),
            enable_explain=True)

    def _build(cfg):
        cluster, lat, bw = build_fake_cluster(
            ClusterSpec(num_nodes=num_nodes, seed=seed))
        loop = SchedulerLoop(cluster, cfg, method="parallel")
        loop.encoder.set_network(lat, bw)
        feed_metrics(cluster, loop.encoder,
                     np.random.default_rng(seed + 1))
        return loop

    def _workload(cfg, n, wseed):
        return generate_workload(
            WorkloadSpec(num_pods=n, seed=wseed, services=8,
                         peer_fraction=0.5),
            scheduler_name=cfg.scheduler_name)

    def _drain_timed(loop, pods, shadow=False, shadow_ms=None):
        cycle_ms = []

        def _tick():
            t0 = time.perf_counter()
            loop.run_once()
            cycle_ms.append((time.perf_counter() - t0) * 1e3)
            if shadow:
                # The shadow serving posture, run every wave so the
                # cost sampling is dense (production spreads this over
                # the maintain cadence): harvest outcomes, join +
                # train, shadow-rank every fresh explain.  Timed
                # separately like the quality leg's harvest_ms — it is
                # maintain-cadence work, not a serving stage; the gate
                # itself (a 120 s-cadence eval) has its own legs
                # below.
                t1 = time.perf_counter()
                loop.quality.harvest(loop.encoder)
                loop._policy_train_tick()
                fresh = [
                    r for r in loop.flight.explains()
                    if float(r.get("t_wall", 0.0))
                    > loop._policy_shadow_twall]
                for rec in fresh:
                    loop.policy.shadow_rank(rec)
                if fresh:
                    loop._policy_shadow_twall = max(
                        float(r.get("t_wall", 0.0)) for r in fresh)
                shadow_ms.append((time.perf_counter() - t1) * 1e3)

        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            _tick()
        while len(loop.queue) or loop._pipe_inflight is not None:
            _tick()
        loop.flush_binds()
        loop.stop_bind_worker()
        return cycle_ms

    def _placements(loop):
        return sorted((b.namespace, b.pod_name, b.node_name)
                      for b in loop.client.bindings)

    # Warm the EXACT config (enable_explain is part of the jit static
    # key, so _warm_like's default-config warm would compile the
    # wrong program and bill XLA to leg A).
    wloop = _build(_cfg())
    for n_warm in (2 * batch, min(batch, 8)):
        wloop.client.add_pods(_workload(wloop.cfg, n_warm, seed + 888))
        wloop.run_until_drained()

    # Leg A: policy off (the enable_learned_score=False posture).
    cfg_a = _cfg()
    loop_a = _build(cfg_a)
    cycles_a = _drain_timed(loop_a, _workload(cfg_a, num_pods,
                                              seed + 5))
    bindings_a = _placements(loop_a)

    # Leg B: identical seeds, policy + dataset + observer attached
    # directly (same cfg shape — flipping cfg flags would change the
    # jit key and bill a recompile as shadow overhead).
    cfg_b = _cfg()
    loop_b = _build(cfg_b)
    loop_b.quality = QualityObserver(cfg_b)
    policy = ScoringPolicy(cfg_b, seed=seed)
    loop_b.policy = policy
    loop_b.policy_dataset = PolicyDataset(cfg_b, policy.k_pad)
    shadow_ms: list[float] = []
    cycles_b = _drain_timed(loop_b, _workload(cfg_b, num_pods,
                                              seed + 5), shadow=True,
                            shadow_ms=shadow_ms)
    bindings_b = _placements(loop_b)
    bit_identical = bindings_a == bindings_b

    p50_a = float(np.percentile(cycles_a, 50)) if cycles_a else 0.0
    p50_b = float(np.percentile(cycles_b, 50)) if cycles_b else 0.0
    # On the serving path the policy adds only counter reads at the
    # commit span; the shadow/train work above is maintain-cadence and
    # runs OUTSIDE the cycle timer.  The honest per-cycle overhead is
    # therefore the measured shadow block amortized over the cycle —
    # the raw A/B p50 ratio is reported beside it but on a 2-leg
    # sequential run it is dominated by machine noise, exactly like
    # the quality leg's harvest_ms split.
    shadow_p50 = float(np.median(shadow_ms)) if shadow_ms else 0.0
    overhead = (shadow_p50 / p50_a) if p50_a else 0.0
    ab_inflation = max(0.0, p50_b / p50_a - 1.0) if p50_a else 0.0
    explains = loop_b.flight.explains()

    # Seeded scenario trace: heterogeneous cluster whose edge class
    # carries degraded links, plus link-drift bursts during the
    # replay — the drifted world the promotion claim is made on.
    spec = ScenarioSpec(
        seed=seed, duration_s=duration_s, base_rate=base_rate,
        diurnal_amplitude=0.3, day_s=max(duration_s / 2.0, 30.0),
        gang_fraction=0.0, longrun_fraction=0.003,
        serving_lifetime_s=12.0, batch_lifetime_s=6.0,
        gang_lifetime_s=10.0, lifetime_floor_s=2.0,
        link_burst_rate_per_s=0.02, link_burst_duration_s=10.0,
        node_churn_rate_per_s=0.0, node_down_duration_s=20.0,
        state_fault_rate_per_s=0.0, chaos_seed=seed + 17,
        cluster=ClusterSpec(
            num_nodes=num_nodes, seed=seed,
            node_classes=(
                NodeClassSpec("std", 0.5),
                NodeClassSpec("edge", 0.5, lat_scale=4.0,
                              bw_scale=0.25),
            )))
    tmp = tempfile.mkdtemp(prefix="policy_trace_")
    trace_path = os.path.join(tmp, "trace.jsonl.gz")
    generate_trace(spec, trace_path)
    rkw = dict(batch=batch, oracle_sample=oracle_sample,
               rebalance=False, state_faults=False)

    try:
        # Gate proof 1: the seeded LOSER.  Zeroing the peer terms is
        # the candidate a log-overfit policy plausibly produces (net
        # signal is the noisiest term); the records leg must refuse
        # it on the recorded evidence alone.
        incumbent = cfg_b.weights
        loser = dataclasses.replace(incumbent, peer_bw=0.0,
                                    peer_lat=0.0)
        reject_decision = evaluate_candidate(
            cfg_b, loser, incumbent, explains,
            trace_path=trace_path, k_pad=policy.k_pad,
            replay_kwargs=rkw)

        # Gate proof 2: the seeded WINNER.  Net-blind incumbent vs
        # the net-aware candidate on the SAME trace — the authority
        # is the replay scorecard, so the records leg is given no
        # evidence (these explains were recorded under a different
        # incumbent and would be noise, not signal).
        inc_blind = dataclasses.replace(REPLAY_WEIGHTS, peer_bw=0.0,
                                        peer_lat=0.0)
        promote_decision = evaluate_candidate(
            cfg_b, REPLAY_WEIGHTS, inc_blind, [],
            trace_path=trace_path, margin=0.005,
            k_pad=policy.k_pad, replay_kwargs=rkw)
    finally:
        try:
            os.remove(trace_path)
            os.rmdir(tmp)
        except OSError:
            pass

    if promote_decision.promote:
        policy.note_promotion(promote_decision.to_dict(),
                              promote_decision.candidate_weights)
    inc_ratio = promote_decision.incumbent_ratio
    cand_ratio = promote_decision.candidate_ratio
    recovered = ((cand_ratio - inc_ratio)
                 / max(1.0 - inc_ratio, 1e-9)
                 if inc_ratio >= 0.0 and cand_ratio >= 0.0 else 0.0)

    doc = {
        "metric": "policy_gate",
        "value": round(float(recovered), 6),
        "unit": "fraction_of_oracle_bw_gain_recovered",
        "seed": seed,
        "detail": {
            "num_nodes": num_nodes,
            "num_pods": num_pods,
            "batch": batch,
            "cycle_ms_p50_off": p50_a,
            "cycle_ms_p50_on": p50_b,
            "ab_p50_inflation": float(ab_inflation),
            "shadow_ms_p50": shadow_p50,
            "shadow_ms_p99": (float(np.percentile(shadow_ms, 99))
                              if shadow_ms else 0.0),
            "shadow_samples": len(shadow_ms),
            "explains_recorded": len(explains),
            "trace": {"duration_s": float(duration_s),
                      "base_rate": float(base_rate),
                      "oracle_sample": int(oracle_sample)},
            "policy": {
                "shadow_overhead_fraction": float(overhead),
                "shadow_overhead_under_2pct": bool(overhead < 0.02),
                "disabled_bit_identical": bool(bit_identical),
                "gate_rejects_loser":
                    bool(not reject_decision.promote),
                "rejection": reject_decision.to_dict(),
                "promoted": bool(promote_decision.promote),
                "promotion": promote_decision.to_dict(),
                "incumbent_bw_ratio_vs_oracle": float(inc_ratio),
                "candidate_bw_ratio_vs_oracle": float(cand_ratio),
                "oracle_gain_recovered_fraction": float(recovered),
                "shadow_disagreement_rate":
                    float(policy.disagreement_rate()),
                "summary": policy.summary(),
            },
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "policy.json", doc, artifacts)
    return SuiteResult("policy", doc, artifacts)


def run_fleet_config(out_dir: str | None = None,
                     tenants: int = 8,
                     num_nodes: int = 48,
                     pods_per_tenant: int = 256,
                     batch: int = 16,
                     seed: int = 0,
                     duration_s: float = 20.0,
                     base_rate: float = 20.0,
                     oracle_sample: int = 128,
                     gate_every: int = 64,
                     gate_cap: int = 640,
                     recipient_offset: int = 5,
                     transfer_leg: bool = True) -> SuiteResult:
    """Fleet-of-clusters leg (ISSUE 16): many logical clusters in one
    batched device state, with cross-cluster policy transfer.

    Three legs in one artifact:

    - **serving isolation (facade A/B)** — K tenants drain identical
      workloads twice, once through K solo SchedulerLoops (the
      one-scheduler-instance-per-cluster deployment the fleet
      replaces) and once through the FleetServer facade (same loop
      code per tenant, one vmapped dispatch per bucket cycle).
      Every tenant's placements must be BYTE-IDENTICAL across the two
      runs; per-tenant score p99 and the SLOEngine snapshot come from
      this leg.  Both facade wall-clocks are reported — on a 1-core
      CPU host the facade's win is bounded by the per-tenant host
      work it deliberately keeps identical to solo.
    - **device-state A/B (the consolidation headline)** — the same K
      pod streams, pre-encoded, drive the batched device state
      directly: one ``fleet_fused_step`` chain (states device-
      resident and donated, batches pre-marshalled along the cluster
      axis — symmetric with the solo chains' pre-encoded batches)
      versus each tenant's own solo ``fused_schedule_step`` chain
      with a per-batch dispatch.  The
      headline ``aggregate_pods_per_sec`` is the batched backend's
      rate over all K tenants; ``single_tenant_pods_per_sec`` is the
      measured serving rate of ONE per-cluster scheduler instance
      (facade leg's solo loops — encode + dispatch + bind each
      cycle, the deployment the motivation says wastes the chip).
      The bar: one shared backend must sustain >= 4x the single-
      instance rate, i.e. it can absorb >= 8 tenant frontends
      without becoming the bottleneck.
    - **transfer (warm vs cold examples-to-promotion)** — a donor
      tenant cold-trains on a seeded decision stream until its
      candidate wins its OWN counterfactual-replay gate on its own
      seeded scenario trace, then registers in the TransferRegistry.
      A recipient tenant (similar topology fingerprint) then runs the
      same protocol twice from identical seeds: cold versus
      warm-started from the registry's closest donor.  Promotion
      stays strictly per-tenant — the warm leg still has to win the
      recipient's own gate; what transfer buys is strictly fewer
      training examples to get there.  The decision stream is a
      seeded synthetic explain stream whose hindsight-best choice is
      net-dominant (deterministic and regenerable); the GATE is the
      real two-leg counterfactual replay on the tenant's trace.
    """
    import tempfile

    from kubernetesnetawarescheduler_tpu.bench.envinfo import bench_env
    from kubernetesnetawarescheduler_tpu.core.assign import (
        fused_schedule_step,
    )
    from kubernetesnetawarescheduler_tpu.core.state import stack_trees
    from kubernetesnetawarescheduler_tpu.fleet import (
        FleetServer,
        TransferRegistry,
        fleet_fused_step,
        node_bucket,
    )
    from kubernetesnetawarescheduler_tpu.policy.model import (
        NUM_TERMS,
        ScoringPolicy,
    )
    from kubernetesnetawarescheduler_tpu.policy.replay_eval import (
        evaluate_candidate,
    )
    from kubernetesnetawarescheduler_tpu.scenario.generate import (
        ScenarioSpec,
        generate_trace,
    )

    bucket = node_bucket(num_nodes, 64)
    # Per-tenant SLO targets must be sized to the SHARED dispatch
    # wall (every lane in a bucket pays the whole bucket's device
    # call — the noisy-neighbor runbook in docs/OPERATIONS.md), so
    # the score p99 target here is the solo 5 ms target scaled for a
    # full bucket on this host, not the solo default.
    cfg = SchedulerConfig(
        max_nodes=bucket, max_pods=batch, max_peers=4,
        enable_explain=False, enable_slo=True,
        slo_eval_interval_s=0.05, slo_score_p99_ms=10.0,
        fleet_bucket_min=bucket,
        queue_capacity=max(300, pods_per_tenant))

    def _tenant_cluster(k):
        cluster, lat, bw = build_fake_cluster(
            ClusterSpec(num_nodes=num_nodes, seed=seed + 10 + k))
        return cluster, lat, bw

    def _attach(loop, k, lat, bw):
        loop.encoder.set_network(lat, bw)
        feed_metrics(loop.client, loop.encoder,
                     np.random.default_rng(seed + 100 + k))

    def _workload(k):
        return generate_workload(
            WorkloadSpec(num_pods=pods_per_tenant,
                         seed=seed + 1000 + k, services=4,
                         peer_fraction=0.5),
            scheduler_name=cfg.scheduler_name)

    def _placements(loop):
        return sorted((b.namespace, b.pod_name, b.node_name)
                      for b in loop.client.bindings)

    def _drive_solo(loop, pods):
        t0 = time.perf_counter()
        for start in range(0, len(pods), batch):
            loop.client.add_pods(pods[start:start + batch])
            loop.run_once()
        while len(loop.queue):
            loop.run_once()
        return time.perf_counter() - t0

    # -- leg 1: facade A/B (isolation + per-tenant SLO) ---------------

    # Warm the EXACT solo program outside any timed drain.
    wcl, wlat, wbw = _tenant_cluster(99)
    wloop = SchedulerLoop(wcl, cfg, method="parallel",
                          burst_batches=1)
    _attach(wloop, 99, wlat, wbw)
    wloop.client.add_pods(_workload(99)[:2 * batch])
    wloop.run_until_drained()

    solo_walls, solo_placements, solo_loops = [], [], []
    for k in range(tenants):
        cluster, lat, bw = _tenant_cluster(k)
        loop = SchedulerLoop(cluster, cfg, method="parallel",
                             burst_batches=1)
        _attach(loop, k, lat, bw)
        solo_walls.append(_drive_solo(loop, _workload(k)))
        solo_placements.append(_placements(loop))
        solo_loops.append(loop)

    # Warm the fleet program (same lane capacity, throwaway tenants).
    wfleet = FleetServer()
    for k in range(tenants):
        cluster, lat, bw = _tenant_cluster(200 + k)
        t = wfleet.add_tenant(f"warm-{k}", cluster, cfg,
                              n_nodes=num_nodes, burst_batches=1)
        _attach(t.loop, 200 + k, lat, bw)
        t.loop.client.add_pods(_workload(200 + k)[:batch])
    wfleet.step()
    wfleet.close()

    fleet = FleetServer()
    ften = []
    for k in range(tenants):
        cluster, lat, bw = _tenant_cluster(k)
        t = fleet.add_tenant(f"tenant-{k:02d}", cluster, cfg,
                             n_nodes=num_nodes, burst_batches=1)
        _attach(t.loop, k, lat, bw)
        ften.append((t, _workload(k)))
    t0 = time.perf_counter()
    start = 0
    while True:
        moved = False
        for t, pods in ften:
            chunk = pods[start:start + batch]
            if chunk:
                t.loop.client.add_pods(chunk)
                moved = True
        start += batch
        if not moved and not any(len(t.loop.queue) for t, _ in ften):
            break
        while any(len(t.loop.queue) for t, _ in ften):
            fleet.step()
    fleet_wall = time.perf_counter() - t0

    per_tenant = {}
    identical_flags = []
    for k, (t, _pods) in enumerate(ften):
        loop = t.loop
        same = _placements(loop) == solo_placements[k]
        identical_flags.append(same)
        timer = loop.timer
        per_tenant[t.name] = {
            "bucket_nodes": t.bucket_nodes,
            "placements": len(loop.client.bindings),
            "bit_identical_to_solo": bool(same),
            "score_p99_ms": (
                float(timer.percentile("score_assign", 99) * 1e3)
                if timer.count("score_assign") else 0.0),
            "slo": (loop.slo.snapshot()
                    if loop.slo is not None else {}),
        }
    isolation = all(identical_flags) and len(identical_flags) > 0

    solo_rates = [pods_per_tenant / w for w in solo_walls if w > 0]
    single_rate = float(np.mean(solo_rates)) if solo_rates else 0.0
    facade_agg = (tenants * pods_per_tenant / fleet_wall
                  if fleet_wall > 0 else 0.0)
    fleet_summary = fleet.summary()
    fleet.close()

    # -- leg 2: device-state A/B (the consolidation headline) ---------

    # Pre-encode each tenant's stream against a FRESH encoder (the
    # admission work both chains share), then race the chains.
    chains = []
    for k in range(tenants):
        cluster, lat, bw = _tenant_cluster(k)
        loop = SchedulerLoop(cluster, cfg, method="parallel",
                             burst_batches=1)
        _attach(loop, k, lat, bw)
        pods = _workload(k)
        batches = [
            loop.encoder.encode_pods(pods[i:i + batch],
                                     node_of=lambda *_: None,
                                     lenient=True)
            for i in range(0, len(pods), batch)]
        state, version = loop.encoder.snapshot_versioned()
        static = loop._static_for(state, version)
        chains.append((state, static, batches))
    n_cycles = min(len(c[2]) for c in chains)

    import jax as _jax
    import jax.numpy as _jnp

    def _copy(tree):
        return _jax.tree_util.tree_map(_jnp.copy, tree)

    # Solo chains: per-batch dispatch per tenant (compile, then time).
    st0, static0, b0 = chains[0]
    s, a, _r = fused_schedule_step(_copy(st0), b0[0], cfg, static0)
    _jax.block_until_ready(a)
    t0 = time.perf_counter()
    for state, static, batches in chains:
        s = _copy(state)
        for b in batches[:n_cycles]:
            s, a, _r = fused_schedule_step(s, b, cfg, static)
        _jax.block_until_ready(a)
    solo_chain_wall = time.perf_counter() - t0
    solo_chain_rate = (tenants * n_cycles * batch / solo_chain_wall
                       if solo_chain_wall > 0 else 0.0)

    # Fleet chain: one vmapped dispatch per cycle, states resident and
    # donated.  The cluster-axis stack of each cycle's K pod batches
    # is staged OUTSIDE the wall, symmetric with the solo leg: both
    # chains consume pre-marshalled batches (a production fleet
    # ingest writes the stacked batch directly at encode time), so
    # the wall measures exactly what differs — K dispatches per cycle
    # versus one.
    statics = _jax.tree_util.tree_map(
        lambda *ls: _jnp.stack([_jnp.asarray(x) for x in ls]),
        *[c[1] for c in chains])
    stacked = [stack_trees([chains[k][2][c] for k in range(tenants)])
               for c in range(n_cycles)]
    states = stack_trees([_copy(c[0]) for c in chains])
    states, a, _r = fleet_fused_step(states, stacked[0], statics, cfg)
    _jax.block_until_ready(a)
    states = stack_trees([_copy(c[0]) for c in chains])
    t0 = time.perf_counter()
    for c in range(n_cycles):
        states, a, _r = fleet_fused_step(states, stacked[c], statics,
                                         cfg)
    _jax.block_until_ready(a)
    fleet_chain_wall = time.perf_counter() - t0
    aggregate_rate = (tenants * n_cycles * batch / fleet_chain_wall
                      if fleet_chain_wall > 0 else 0.0)

    speedup = (aggregate_rate / single_rate if single_rate else 0.0)

    # -- leg 3: transfer (warm vs cold examples-to-promotion) ---------

    # Nearly net-blind serving weights: the learned net multiplier is
    # what the gate has to promote.
    tweights = ScoreWeights(
        cpu=0.5, mem=0.5, net_tx=0.0, net_rx=0.0, bandwidth=1.0,
        disk=0.0, peer_bw=0.15, peer_lat=0.1, balance=0.5,
        soft_affinity=1.0, spread=0.5)
    tcfg = SchedulerConfig(
        max_nodes=128, max_pods=32, max_peers=4, weights=tweights,
        policy_min_examples=32, enable_explain=True)

    def _scenario(scn_seed, n_nodes):
        return ScenarioSpec(
            seed=scn_seed, duration_s=duration_s,
            base_rate=base_rate, diurnal_amplitude=0.3, day_s=30.0,
            gang_fraction=0.0, longrun_fraction=0.003,
            serving_lifetime_s=12.0, batch_lifetime_s=6.0,
            gang_lifetime_s=10.0, lifetime_floor_s=2.0,
            peer_fraction=0.85, max_peers=3, services=8,
            netbw_range=(0.2, 1.5),
            link_burst_rate_per_s=0.02, link_burst_duration_s=10.0,
            node_churn_rate_per_s=0.0, node_down_duration_s=20.0,
            state_fault_rate_per_s=0.0, chaos_seed=scn_seed + 17,
            cluster=ClusterSpec(
                num_nodes=n_nodes, seed=scn_seed,
                node_classes=(
                    NodeClassSpec("std", 0.4),
                    NodeClassSpec("edge", 0.6, lat_scale=6.0,
                                  bw_scale=0.15))))

    # Seeded decision stream with a net-dominant hindsight optimum.
    oracle_terms = np.array([1.0, 4.0, 1.0, 1.0, 1.0], np.float64)

    def _stream(policy, rng, n):
        k_pad = policy.k_pad
        comps = rng.normal(0.5, 1.0, size=(n, k_pad, NUM_TERMS)
                           ).astype(np.float32)
        feas = np.ones((n, k_pad), np.float32)
        cls = np.zeros((n, k_pad), np.int32)
        tgt = np.argmax(comps @ oracle_terms, axis=1).astype(np.int32)
        policy.add_examples(comps, feas, tgt, cls)

    def _examples_to_promotion(policy, rng, trace_path, rkw):
        evals = []
        while True:
            cand = policy.to_score_weights(tcfg.weights)
            decision = evaluate_candidate(
                tcfg, cand, tcfg.weights, [], trace_path=trace_path,
                margin=0.02, k_pad=policy.k_pad, replay_kwargs=rkw)
            evals.append({
                "examples": int(policy.examples_total),
                "promote": bool(decision.promote),
                "incumbent_ratio": float(decision.incumbent_ratio),
                "candidate_ratio": float(decision.candidate_ratio),
                "reason": decision.reason,
            })
            if decision.promote:
                return int(policy.examples_total), decision, evals
            if policy.examples_total >= gate_cap:
                return None, decision, evals
            _stream(policy, rng, gate_every)
            policy.train(16)

    if not transfer_leg:
        # Full-shape-only: every gate eval recompiles the fused
        # step for its candidate weights (weights are static to
        # the kernel), which dominates the structural smoke's
        # wall -- and the warm-vs-cold bar is full-shape-only
        # anyway.
        transfer_block = {"skipped":
                          "transfer leg is full-shape-only"}
    else:
        tmp = tempfile.mkdtemp(prefix="fleet_transfer_")
        donor_trace = os.path.join(tmp, "donor.jsonl.gz")
        recip_trace = os.path.join(tmp, "recipient.jsonl.gz")
        generate_trace(_scenario(seed, 96), donor_trace)
        # The recipient is a DIFFERENT seeded tenant of the same scenario
        # family (same size/shape spec, its own cluster layout and
        # arrival stream).  ``recipient_offset`` pins which sibling: the
        # family's per-seed incumbent strength varies a lot (edge-node
        # draws decide how much net-awareness is worth), and the default
        # picks a seed whose incumbent profile matches the donor's —
        # i.e. a recipient the registry's fingerprint matching would
        # actually pair with this donor.
        generate_trace(_scenario(seed + recipient_offset, 96),
                       recip_trace)
        rkw = dict(batch=32, oracle_sample=oracle_sample,
                   rebalance=False, state_faults=False)
        registry = TransferRegistry()
        try:
            # Donor tenant: cold-train to promotion on ITS OWN gate, then
            # register as a transfer donor.
            donor = ScoringPolicy(tcfg, seed=seed + 1)
            e_donor, d_donor, donor_evals = _examples_to_promotion(
                donor, np.random.default_rng(seed + 11), donor_trace,
                rkw)
            if d_donor.promote:
                donor.note_promotion(d_donor.to_dict(),
                                     d_donor.candidate_weights)
            donor_features = {"nodes": 96.0, "zones": 2.0,
                              "lat_mean": 2.0, "bw_mean": 1.0}
            registry.register("donor", donor_features, donor)

            # Recipient, cold leg: identical seeds, no transfer.
            cold = ScoringPolicy(tcfg, seed=seed + 2)
            e_cold, _d_cold, cold_evals = _examples_to_promotion(
                cold, np.random.default_rng(seed + 12), recip_trace, rkw)

            # Recipient, warm leg: identical seeds, warm-started from the
            # registry's closest donor; still has to win its OWN gate.
            warm = ScoringPolicy(tcfg, seed=seed + 2)
            recip_features = {"nodes": 96.0, "zones": 2.0,
                              "lat_mean": 2.1, "bw_mean": 0.9}
            donor_rec = registry.warm_start(warm, recip_features)
            e_warm, _d_warm, warm_evals = _examples_to_promotion(
                warm, np.random.default_rng(seed + 12), recip_trace, rkw)
        finally:
            for p in (donor_trace, recip_trace):
                try:
                    os.remove(p)
                except OSError:
                    pass
            try:
                os.rmdir(tmp)
            except OSError:
                pass

        warm_lt_cold = (e_warm is not None and e_cold is not None
                        and e_warm < e_cold)
        transfer_block = {
            "examples_to_promotion_donor": e_donor,
            "examples_to_promotion_cold": e_cold,
            "examples_to_promotion_warm": e_warm,
            "warm_lt_cold": bool(warm_lt_cold),
            "donor_used": (donor_rec.to_dict()
                           if donor_rec is not None
                           else None),
            "donor_evals": donor_evals,
            "cold_evals": cold_evals,
            "warm_evals": warm_evals,
            "registry": registry.summary(),
        }

    doc = {
        "metric": "fleet_consolidation",
        "value": round(float(speedup), 3),
        "unit": "x_single_tenant_rate",
        "seed": seed,
        "detail": {
            "tenants": tenants,
            "num_nodes_per_tenant": num_nodes,
            "bucket_nodes": bucket,
            "pods_per_tenant": pods_per_tenant,
            "batch": batch,
            "total_pods": tenants * n_cycles * batch,
            "fleet": {
                "isolation_bit_identical": bool(isolation),
                "tenants": per_tenant,
                "aggregate_pods_per_sec": round(aggregate_rate, 1),
                "single_tenant_pods_per_sec": round(single_rate, 1),
                "speedup": round(float(speedup), 3),
                "speedup_over_4x": bool(speedup >= 4.0),
                "methodology": {
                    "single_tenant_rate":
                        "one per-cluster SchedulerLoop serving its "
                        "own workload: host encode + device dispatch "
                        "+ bind every cycle (the deployment the "
                        "fleet consolidates)",
                    "aggregate_rate":
                        "the batched device state: K tenants' "
                        "pre-encoded streams through one vmapped "
                        "fused score->resolve->commit chain, states "
                        "device-resident and donated; batches are "
                        "pre-marshalled along the cluster axis "
                        "outside the wall, symmetric with the solo "
                        "chains' pre-encoded batches",
                },
                "facade": {
                    "aggregate_pods_per_sec": round(facade_agg, 1),
                    "solo_aggregate_pods_per_sec": round(
                        tenants * pods_per_tenant / sum(solo_walls)
                        if sum(solo_walls) > 0 else 0.0, 1),
                    "speedup_vs_solo": round(
                        facade_agg * sum(solo_walls)
                        / (tenants * pods_per_tenant), 3)
                        if sum(solo_walls) > 0 else 0.0,
                    "wall_s": round(fleet_wall, 3),
                    "dispatches_total": int(
                        fleet_summary["dispatches_total"]),
                    "dispatch_lanes_total": int(
                        fleet_summary["dispatch_lanes_total"]),
                },
                "device_chain": {
                    "solo_wall_s": round(solo_chain_wall, 3),
                    "fleet_wall_s": round(fleet_chain_wall, 3),
                    "solo_chain_pods_per_sec": round(
                        solo_chain_rate, 1),
                    "cycles_per_tenant": int(n_cycles),
                },
                "transfer": transfer_block,
            },
            "bench_env": bench_env(),
        },
    }
    artifacts: list[str] = []
    write_artifact(out_dir, "fleet.json", doc, artifacts)
    return SuiteResult("fleet", doc, artifacts)


CONFIGS: dict[str, Callable[..., SuiteResult]] = {
    "density": run_density_config,
    "custom_network": run_custom_network_config,
    "affinity": run_affinity_config,
    "soft_affinity": run_soft_affinity_config,
    "spread": run_spread_config,
    "zone_affinity": run_zone_affinity_config,
    "binpack": run_binpack_config,
    "sidecar": run_sidecar_config,
    "gang": run_gang_config,
    "topology": run_topology_config,
    "integrity": run_integrity_config,
    "quality": run_quality_config,
    "rebalance": run_rebalance_config,
    "reshape": run_reshape_config,
    "scenario": run_scenario_config,
    "policy": run_policy_config,
    "fleet": run_fleet_config,
}

# Reduced shapes for smoke runs / CPU CI.
SMALL = {
    "density": dict(num_nodes=64, num_pods=128, batch=32),
    "custom_network": dict(num_nodes=128, pod_counts=(5,)),
    "affinity": dict(num_nodes=64, num_pods=128, batch=32),
    "soft_affinity": dict(num_nodes=64, num_pods=256, batch=32,
                          deep=False),
    "spread": dict(num_nodes=64, num_pods=256, batch=32),
    "zone_affinity": dict(num_nodes=64, num_pods=256, batch=32),
    "binpack": dict(num_nodes=64, num_pods=256, batch=32),
    "sidecar": dict(num_nodes=128, num_apps=48, batch=32),
    "gang": dict(num_nodes=128, num_gangs=6, member_counts=(4, 8),
                 filler_pods=32, batch=32, overhead_pods=64),
    "topology": dict(num_nodes=128, cycles=40, probe_budget=32,
                     num_gangs=4),
    "integrity": dict(num_nodes=64, num_pods=96, batch=32),
    "quality": dict(num_nodes=64, num_pods=96, batch=32),
    "rebalance": dict(num_nodes=64, num_pods=96, batch=32,
                      drift_nodes=8, rounds=4),
    "reshape": dict(num_nodes=32, num_gangs=4, gang_size=4,
                    filler_pods=16, batch=32, rounds=6),
    "scenario": dict(num_nodes=64, duration_s=30.0, base_rate=30.0,
                     batch=32, gang_fraction=0.01,
                     oracle_sample=64),
    "policy": dict(num_nodes=64, num_pods=96, batch=32,
                   duration_s=20.0, base_rate=20.0,
                   oracle_sample=64),
    # Structural smoke only (isolation bit-identity is asserted at
    # any size; the 4x and warm-vs-cold bars are full-shape-only) —
    # sized for the tier-1 wall, which has no headroom to spare.
    "fleet": dict(tenants=4, num_nodes=24, pods_per_tenant=32,
                  batch=8, transfer_leg=False),
}


def run_suite(configs: Sequence[str] | None = None,
              out_dir: str | None = None,
              small: bool = False) -> list[SuiteResult]:
    names = list(configs) if configs else list(CONFIGS)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    results = []
    for name in names:
        kwargs = dict(SMALL[name]) if small else {}
        results.append(CONFIGS[name](out_dir=out_dir, **kwargs))
    return results


def main(argv: Sequence[str] | None = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="all",
                    help=f"one of {', '.join(CONFIGS)} or 'all'")
    ap.add_argument("--out", default="bench_artifacts")
    ap.add_argument("--small", action="store_true",
                    help="reduced shapes for smoke runs")
    args = ap.parse_args(argv)
    names = list(CONFIGS) if args.config == "all" else [args.config]
    for res in run_suite(names, out_dir=args.out, small=args.small):
        print(json.dumps(res.to_dict()))


if __name__ == "__main__":
    main()
