"""Shared full-daemon drain harness (serve_smoke / bind_budget).

One implementation of the end-to-end daemon measurement — serve.py
(HTTP watch -> encode -> score -> bind POSTs) draining a backlog from
the in-repo fake apiserver — used by BOTH ``tools/tpu_legs.py
serve_smoke`` (hardware leg) and ``tools/bind_budget.py`` (bind-path
budget).  Round 5 found the two near-verbatim copies had already
drifted AND both encoded the jit-shape warm contract by hand; a
missed warm shape silently re-introduces the in-window burst-program
XLA compile that made round 4's serve_smoke read 69 binds/s.

The reference's analogous loop is ``Schedule()`` + POST Binding
(scheduler.go:189-237) against a live API server; this harness is the
same wire contract against ``tests/test_kubeclient.FakeApiServer``.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time


def drain_daemon(n_nodes: int = 512, n_pods: int = 2048,
                 deadline_s: float = 900.0,
                 collect_phases: bool = False) -> dict:
    """Drain ``n_pods`` through the full daemon; returns a dict with
    ``binds_per_sec`` / ``wall_s`` (post-compile: the warm passes
    below pay every jit shape before the timed window).

    ``n_nodes`` must size capacity above ``n_pods``: the default
    ``_pod_json`` pod fits ~5.3x per default ``_node_json`` node, so
    undersizing makes the tail legitimately unschedulable and the
    drain times out on arithmetic, not a bug.

    ``collect_phases=True`` additionally scrapes the daemon's own
    /metrics for the per-phase latency budget (encode / score_assign
    / bind / bind_net / burst_wall sums and counts).
    """
    from kubernetesnetawarescheduler_tpu import serve
    from tests.test_kubeclient import (
        FakeApiServer,
        _node_json,
        _pod_json,
    )

    tmp = tempfile.mkdtemp()
    cfg_path = os.path.join(tmp, "cfg.json")
    with open(cfg_path, "w") as f:
        json.dump({"max_nodes": n_nodes, "max_pods": 256,
                   "max_peers": 4,
                   "queue_capacity": n_pods + 256}, f)

    def make_api(num_pods: int) -> FakeApiServer:
        api = FakeApiServer()
        api.nodes = [_node_json(f"node-{i:04d}")
                     for i in range(n_nodes)]
        api.node_events = [{"type": "ADDED", "object": nd}
                           for nd in api.nodes]
        api.pods = [_pod_json(f"pod-{i:05d}")
                    for i in range(num_pods)]
        api.pod_events = [{"type": "ADDED", "object": p}
                          for p in api.pods]
        return api

    def make_argv(api: FakeApiServer) -> list[str]:
        uds = os.path.join(tempfile.mkdtemp(), "scorer.sock")
        return ["--cluster", f"kube:{api.url}", "--kube-token", "t",
                "--uds", uds, "--config", cfg_path, "--async-bind"]

    # Warm passes: BOTH jit shapes.  A >=2-batch queue pops as one
    # backlog burst padded to burst_batches x max_pods (its own XLA
    # program); the drain tail runs the per-batch program.  512
    # queued pods (2 batches) compiles the burst shape, 8 the
    # per-batch shape.
    for warm_pods in (2 * 256, 8):
        api = make_api(warm_pods)
        try:
            rc = serve.main(make_argv(api) + ["--once"])
            if rc != 0:
                raise SystemExit(f"warm serve rc={rc}")
        finally:
            api.stop()

    # Timed pass: the daemon proper (no --once), polled until the
    # backlog is drained.  The serve thread has no stop hook off the
    # main thread; callers run in a throwaway process.
    api = make_api(n_pods)
    argv = make_argv(api)
    t0 = time.perf_counter()
    th = threading.Thread(target=serve.main, args=(argv,), daemon=True)
    th.start()
    deadline = time.monotonic() + deadline_s
    while len(api.bindings) < n_pods and time.monotonic() < deadline:
        if not th.is_alive():
            raise SystemExit(
                f"serve daemon died after {len(api.bindings)} binds")
        time.sleep(0.05)
    wall = time.perf_counter() - t0
    bound = len(api.bindings)
    if bound < n_pods:
        # A deadline exit must NOT report a rate that measures the
        # timeout rather than the drain.
        raise SystemExit(f"only {bound}/{n_pods} pods bound "
                         f"within {wall:.0f}s")
    out = {"nodes": n_nodes, "pods": n_pods, "bound": bound,
           "wall_s": round(wall, 2),
           "binds_per_sec": round(bound / wall, 1),
           "note": "post-compile (burst + per-batch shapes warmed)"}
    if collect_phases:
        phases: dict = {}
        try:
            from kubernetesnetawarescheduler_tpu.api.server import (
                call_uds,
            )

            body = call_uds(argv[argv.index("--uds") + 1], "/metrics",
                            b"", timeout_s=30).decode()
            for line in body.splitlines():
                if line.startswith("netaware_phase_latency_seconds") \
                        and not line.startswith("#"):
                    key = line.split(" ")[0]
                    phases[key] = float(line.rsplit(" ", 1)[1])
        except Exception as exc:  # noqa: BLE001 — budget best-effort
            phases = {"error": f"{type(exc).__name__}: {exc}"}
        out["phase_budget"] = phases
    return out
