"""clusterloader2-style density replay: pods/sec + Score() latency.

The reference's density evidence is committed clusterloader2 output at
90/110/130 containers on the 5-node cluster
(datasets/clusterloader2/*/ResourceUsageSummary_load_*.json).  This
harness replays the same *kind* of experiment as code against the fake
cluster: N nodes, a stream of pending pods, measuring scheduling
throughput and per-cycle score/assign latency percentiles — the
BASELINE.json north-star metrics (>=10k pods/sec, p99 Score() < 5 ms at
5k nodes).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np

from kubernetesnetawarescheduler_tpu.config import SchedulerConfig
from kubernetesnetawarescheduler_tpu.core.loop import SchedulerLoop
from kubernetesnetawarescheduler_tpu.bench.fakecluster import (
    ClusterSpec,
    WorkloadSpec,
    build_fake_cluster,
    feed_metrics,
    generate_workload,
    sample_metrics,
)


@dataclasses.dataclass
class DensityResult:
    num_nodes: int
    pods_submitted: int
    pods_bound: int
    pods_unschedulable: int
    wall_s: float
    pods_per_sec: float
    score_p50_ms: float
    score_p99_ms: float
    encode_p99_ms: float
    bind_p99_ms: float
    # How many independent latency samples back the score percentiles.
    # Host mode: one per cycle.  Pipeline mode: one per chunk arrival
    # (true percentiles).  Monolithic device mode: 1 — the score
    # numbers there are an amortized mean, honestly labeled.
    score_samples: int = 0
    # Conflict-resolution round distribution of assign_parallel, one
    # sample per batch (device/pipeline modes; 0s when unavailable):
    # whether TPU latency is matmul-bound or round-bound is a function
    # of this (VERDICT.md round 2, weak #1).
    rounds_p50: float = 0.0
    rounds_p99: float = 0.0
    rounds_max: int = 0
    # Pipeline-mode residual after the last chunk fetch: the bind work
    # the overlap failed to hide (bind_p99_ms itself is a true
    # percentile over per-batch bind samples, NOT this residual — r5
    # reported the residual AS the p99, 905.74 ms at N=5120).
    bind_tail_ms: float = 0.0
    # Per-stage pipeline budgets (encode/dispatch/device_wait/bind)
    # from the serving loop's PhaseTimer — host mode only; artifacts
    # carry the overlap structure on their face.
    pipeline_budgets: dict = dataclasses.field(default_factory=dict)
    # Incremental device-resident state (r7): static-refresh activity
    # during the measured window.  With no churn (``churn_links=0``)
    # only the initial build registers — static never moves after
    # warmup and the near-zero count is the honest report, not a gap.
    static_refresh_count: int = 0
    static_refresh_p99_ms: float = 0.0
    static_sync_builds: int = 0
    # Staleness of the static actually served at each Score() call
    # (0.0 for a current static; the async-refresh knobs bound it).
    staleness_at_score_p50_ms: float = 0.0
    staleness_at_score_p99_ms: float = 0.0
    # The configured ceiling (cfg.static_max_staleness_s): breaching
    # it forces a synchronous rebuild, so p99 above must sit under it.
    staleness_bound_s: float = 0.0
    # Host→device snapshot traffic: bytes moved by dirty-index scatter
    # updates vs full-array re-uploads (the r5 regression was 100%
    # full_bytes — one link probe re-uploaded the N×N matrices).
    delta_bytes: int = 0
    full_bytes: int = 0
    # Flight-recorder provenance (r8): worst retained cycle span +
    # ring accounting.  bench_check Rule 8 requires this block on any
    # r8+ artifact claiming the p99 bar — a tail-latency claim must be
    # attributable to a concrete cycle, not just a window percentile.
    trace_provenance: dict = dataclasses.field(default_factory=dict)
    # Bind-tail split (r7 satellite): r5 reported a 905.74 ms
    # "bind_p99_ms" that was actually drain serialization.  Split the
    # bind cost by cause: queue wait (assignment fetched, binder
    # busy), client RTT (one _bind_all API round-trip, un-normalized),
    # and transient-bind retries.
    bind_queue_wait_p99_ms: float = 0.0
    bind_rtt_p99_ms: float = 0.0
    bind_retry_count: int = 0
    # Persistent multi-cycle serving (r16): provenance for the
    # amortized device-boundary claim — which K the drain ran, how
    # deep the device wave ring was, how late waves retired — plus the
    # coalesced-bind accounting bench_check Rule 16 requires beside
    # any r16+ p99 claim (zeros/0.0 when multicycle was off).
    multicycle_k: int = 0
    multicycle_queue_depth: int = 0
    multicycle_windows: int = 0
    multicycle_overflow: int = 0
    retire_lag_p99: float = 0.0
    bind_max_inflight: int = 0
    bind_coalesce_window: int = 0
    bind_coalesced_total: int = 0
    bind_inflight_peak: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def _percentile(samples, q: float) -> float:
    ordered = sorted(samples)
    if not ordered:
        return 0.0
    rank = min(len(ordered) - 1,
               max(0, int(round(q / 100.0 * (len(ordered) - 1)))))
    return float(ordered[rank])


def _percentile_ms(samples, q: float) -> float:
    return _percentile(samples, q) * 1e3


def _static_stats(loop: "SchedulerLoop") -> dict:
    """Static-refresh and delta-ingest counters the serving loop and
    its encoder accumulated over the run (all zero when static never
    moved).  ``_static_refresh_ms`` samples are already milliseconds;
    ``_staleness_samples`` are seconds."""
    enc = loop.encoder
    return {
        "static_refresh_count": int(
            getattr(loop, "static_refresh_total", 0)),
        "static_refresh_p99_ms": round(_percentile(
            list(getattr(loop, "_static_refresh_ms", ())), 99), 3),
        "static_sync_builds": int(
            getattr(loop, "static_sync_builds", 0)),
        "staleness_at_score_p50_ms": round(_percentile_ms(
            list(getattr(loop, "_staleness_samples", ())), 50), 3),
        "staleness_at_score_p99_ms": round(_percentile_ms(
            list(getattr(loop, "_staleness_samples", ())), 99), 3),
        "delta_bytes": int(
            getattr(enc, "snapshot_delta_bytes_total", 0)),
        "full_bytes": int(
            getattr(enc, "snapshot_full_bytes_total", 0)),
    }


def _flight_stats(loop: "SchedulerLoop",
                  trace_out: str | None = None) -> dict:
    """Flight-recorder provenance for the artifact: ring accounting
    plus the worst retained cycle span (bench_check Rule 8), and —
    when ``trace_out`` is set — the whole recorder dumped as a
    Perfetto-loadable trace leg (lint: tools/trace_check.py)."""
    flight = getattr(loop, "flight", None)
    if flight is None:
        return {}
    if trace_out:
        with open(trace_out, "w", encoding="utf-8") as fh:
            json.dump(flight.to_chrome_trace(), fh, indent=1,
                      sort_keys=True)
    worst = flight.worst_cycle()
    worst_doc: dict = {}
    if worst is not None:
        worst_doc = {
            "cycle_id": int(worst.cycle_id),
            "dur_ms": round(worst.dur_s * 1e3, 3),
            "path": worst.path,
            "phases": [[name, round(rel * 1e3, 3), round(dur * 1e3, 3)]
                       for name, rel, dur in worst.phases],
        }
    return {
        "trace_provenance": {
            "spans": len(flight),
            "capacity": int(flight.capacity),
            "dropped": int(flight.dropped),
            "worst_cycle": worst_doc,
            "trace_out": trace_out or "",
        },
    }


def _churn_fn(encoder, node_names: list, rng: np.random.Generator,
              churn_links: int):
    """A zero-arg closure that perturbs ``churn_links`` random links
    (probe results, ``update_link``) plus one node's metrics sample —
    the steady measurement drizzle a live cluster sees, which keeps
    ``static_version`` moving so the run exercises the delta-ingest +
    incremental-refresh machinery instead of the churn-free drain
    whose static is computed once and never again."""
    n = len(node_names)

    def tick() -> None:
        for _ in range(churn_links):
            i, j = rng.choice(n, size=2, replace=False)
            encoder.update_link(
                node_names[int(i)], node_names[int(j)],
                lat_ms=float(rng.uniform(0.05, 2.0)),
                bw_bps=float(rng.uniform(1e8, 1e10)))
        encoder.update_metrics(node_names[int(rng.integers(n))],
                               sample_metrics(rng))

    return tick


def _warm_churn_path(loop: "SchedulerLoop", churn_tick,
                     ticks: int = 3) -> None:
    """Pay the delta-ingest / incremental-refresh jit compiles outside
    the timed window (pow2-padded scatter shapes, the delta static
    path — distinct executables from the full-rebuild warmup), then
    zero the refresh counters so the artifact's static_refresh block
    covers the measured steady state only."""
    for _ in range(ticks):
        churn_tick()
        st, ver = loop.encoder.snapshot_versioned()
        loop._static_for(st, ver)
    # Drain any queued async rebuild; the measured run restarts the
    # worker on first use (_ensure_static_worker clears the stop flag).
    loop.stop_static_refresher()
    loop.static_refresh_total = 0
    loop.static_sync_builds = 0
    loop._static_refresh_ms.clear()
    loop._staleness_samples.clear()
    loop.encoder.snapshot_delta_bytes_total = 0
    loop.encoder.snapshot_full_bytes_total = 0


def _drain_with_churn(loop: "SchedulerLoop", churn_tick,
                      max_cycles: int = 10_000) -> int:
    """``run_until_drained`` with churn injected between cycles (host
    mode): every serving cycle is preceded by one churn tick, so each
    ``snapshot_versioned`` sees a moved static version and
    ``_static_for`` runs its refresh path inside the timed window."""
    total = 0
    for _ in range(max_cycles):
        churn_tick()
        n = loop.run_once(timeout=0.0)
        if n == 0 and len(loop.queue) == 0:
            loop.flush_binds()
            if len(loop.queue) == 0:
                break
        total += n
    return total


from kubernetesnetawarescheduler_tpu.core.state import round_up as _round_up


def _overlap_encode() -> bool:
    """Whether pipeline mode overlaps host encode with the device
    drain (``BENCH_ENCODE_OVERLAP``: ``1`` force on, ``0`` force off,
    unset = auto).  Auto enables overlap only on an accelerator
    backend: there the host core sits blocked on chunk fetches while
    the device computes, so the encode producer rides for free.  On
    the CPU backend "device" compute shares the host cores (this box:
    ONE core), and a producer thread just inflates every phase with
    contention — measured 9,787 → 8,079 pods/s at N=1024.

    Auto also requires spare host cores: on a 1-core host the producer
    contends with the dispatch/fetch/bind threads even when the device
    computes off-host — measured on the tunneled v5e (1-core host,
    N=1024): overlap OFF 14,019 pods/s vs ON 10,248."""
    env = os.environ.get("BENCH_ENCODE_OVERLAP", "")
    if env in ("0", "1"):
        return env == "1"
    import jax

    try:
        # Affinity-aware (a container pinned to 1 CPU of a 64-core
        # node must count as 1 core here, or auto re-creates the
        # measured single-core regression).
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    return jax.default_backend() != "cpu" and cores >= 2


def _stream_chunks(stream, chunk_pods: int):
    """Split an already-encoded PodStream into feed-sized chunks
    (pytree slices; used to warm the feed-path executable with the
    same chunk-length sequence the measured run dispatches)."""
    import jax

    for a in range(0, stream.num_pods, chunk_pods):
        yield jax.tree_util.tree_map(
            lambda x: x[a:a + chunk_pods], stream)


def _throwaway_loop(num_nodes: int, seed: int, cfg: SchedulerConfig,
                    method: str,
                    multicycle: int | None = None) -> SchedulerLoop:
    """A warmed-up scheduler loop on a throwaway cluster with compile
    shapes identical to the measured run (used to pay jit compilation
    outside the timed window, in both host and device modes)."""
    wcluster, wlat, wbw = build_fake_cluster(
        ClusterSpec(num_nodes=num_nodes, seed=seed + 999))
    wloop = SchedulerLoop(wcluster, cfg, method=method,
                          multicycle=multicycle)
    wloop.encoder.set_network(wlat, wbw)
    feed_metrics(wcluster, wloop.encoder, np.random.default_rng(seed + 2))
    return wloop


def _multicycle_stats(loop: "SchedulerLoop",
                      cfg: SchedulerConfig) -> dict:
    """Multi-cycle + coalesced-bind accounting the drain accumulated
    (r16).  ``retire_lag_p99`` comes from the loop's LogHistogram —
    exact small-int buckets, same family /metrics exports."""
    lag = getattr(loop, "_retire_lag", None)
    return {
        "multicycle_k": int(getattr(loop, "multicycle", 1)),
        "multicycle_queue_depth": int(
            getattr(cfg, "multicycle_queue_depth", 0)),
        "multicycle_windows": int(
            getattr(loop, "multicycle_windows", 0)),
        "multicycle_overflow": int(
            getattr(loop, "multicycle_overflow_total", 0)),
        "retire_lag_p99": (float(lag.percentile(99))
                           if lag is not None and len(lag) else 0.0),
        "bind_max_inflight": int(
            getattr(cfg, "bind_max_inflight", 1)),
        "bind_coalesce_window": int(
            getattr(cfg, "bind_coalesce_window", 1)),
        "bind_coalesced_total": int(
            getattr(loop, "bind_coalesced_total", 0)),
        "bind_inflight_peak": int(
            getattr(loop, "bind_inflight_peak", 0)),
    }


def run_density(num_nodes: int = 100, num_pods: int = 300,
                batch_size: int = 64, method: str = "parallel",
                seed: int = 0, cfg: SchedulerConfig | None = None,
                warmup: bool = True,
                metric_drop_fraction: float = 0.0,
                mode: str = "host",
                chunk_batches: int = 2,
                score_backend: str = "xla",
                sampler=None, mesh=None,
                pipelined: bool = False,
                churn_links: int = 0,
                multicycle: int = 1,
                bind_coalesce_window: int = 1,
                bind_max_inflight: int = 1,
                trace_out: str | None = None) -> DensityResult:
    """Schedule ``num_pods`` generated pods onto a ``num_nodes`` fake
    cluster; returns throughput/latency stats (compile excluded via a
    warmup cycle).

    ``mode="host"`` drives the live-serving loop as deployed: one
    host↔device round-trip per batch when the queue is shallow, and —
    since round 4's backlog burst mode (SchedulerLoop, burst_batches,
    default 8) — up to 8 batches per dispatch under a deep backlog.
    Host-mode numbers from earlier rounds measured the strictly
    per-batch shape and are not directly comparable.
    ``mode="device"`` runs the whole workload as one
    :func:`~kubernetesnetawarescheduler_tpu.core.replay.replay_stream`
    dispatch — the throughput path; per-batch latency is then reported
    amortized (wall / num_batches) for the score percentiles.

    ``sampler``, if given, must have a ``start()`` method; it is started
    after warmup/compilation so resource sampling covers only the
    measured serving window (the clusterloader2 analogy: samples are of
    the serving scheduler, not of XLA compiling).

    ``churn_links`` > 0 injects seeded link-probe + metrics churn into
    the measured window (one tick per serving cycle in host mode, one
    per chunk arrival in pipeline mode, one per bind batch in device
    mode), so ``static_version`` keeps moving and the run measures the
    incremental-refresh machinery a live deployment exercises —
    reported via the ``static_refresh_*``/``staleness_*``/``*_bytes``
    result fields.  The default cfg then also turns on
    ``enable_async_static`` (churn with synchronous rebuilds would put
    every refresh back on the serving critical path — the exact r5
    regression this bench exists to detect); an explicitly passed cfg
    keeps its own setting."""
    if cfg is None:
        cfg = SchedulerConfig(
            max_nodes=_round_up(num_nodes, 128),
            max_pods=batch_size,
            max_peers=4,
            queue_capacity=max(300, num_pods + batch_size,
                               multicycle * batch_size),
            score_backend=score_backend,
            enable_async_static=(churn_links > 0),
            multicycle=max(1, multicycle),
            # Ring depth follows K: the bench measures amortization,
            # not the overflow-fallback path (a caller-passed cfg
            # keeps its own — possibly mis-tuned — depth).
            multicycle_queue_depth=max(4, multicycle),
            bind_coalesce_window=max(1, bind_coalesce_window),
            bind_max_inflight=max(1, bind_max_inflight),
        )
    # Effective K: an explicitly-passed cfg keeps its own knob; the
    # param only overrides when the caller actually asked for K>1.
    eff_multicycle = (multicycle if multicycle > 1
                      else int(getattr(cfg, "multicycle", 1)))
    # Coalesced async binds only exist on the bind-worker path: turn
    # the worker on when the knobs ask for coalescing/inflight > 1
    # (pipelined mode already implies it inside SchedulerLoop).
    auto_async_bind = (eff_multicycle > 1 and (
        int(getattr(cfg, "bind_coalesce_window", 1)) > 1
        or int(getattr(cfg, "bind_max_inflight", 1)) > 1))
    cluster, lat, bw = build_fake_cluster(ClusterSpec(num_nodes=num_nodes,
                                                      seed=seed))
    # ``pipelined`` (host mode): the three-stage pipelined serving
    # cycle — encode-ahead thread + deferred fetch + async bind worker
    # (SchedulerLoop pipelined=True).  Assignments are identical to
    # the serial cycle; only the overlap differs.
    loop = SchedulerLoop(cluster, cfg, method=method,
                         pipelined=pipelined,
                         async_bind=auto_async_bind,
                         multicycle=eff_multicycle)
    loop.encoder.set_network(lat, bw)
    rng = np.random.default_rng(seed + 1)
    feed_metrics(cluster, loop.encoder, rng,
                 drop_fraction=metric_drop_fraction)

    pods = generate_workload(WorkloadSpec(num_pods=num_pods, seed=seed),
                             scheduler_name=cfg.scheduler_name)

    if mode in ("device", "pipeline"):
        return _run_density_device(cluster, loop, pods, cfg, method,
                                   num_nodes, seed, warmup, sampler,
                                   chunk_batches=chunk_batches,
                                   pipeline=(mode == "pipeline"),
                                   mesh=mesh, churn_links=churn_links,
                                   trace_out=trace_out)

    if warmup:
        wloop = _throwaway_loop(num_nodes, seed, cfg, method,
                                multicycle=eff_multicycle)
        # TWO warm waves: pop_batch drains everything available, so a
        # single combined wave would compile only the burst program —
        # the measured run's sub-2-batch drain TAIL would then compile
        # assign_parallel inside the timed window.  Wave 1 (2 batches)
        # compiles the burst shape; wave 2 (a lone small batch)
        # compiles the per-batch shape.
        # cfg.max_pods, not batch_size: an explicitly-passed cfg may
        # differ, and the burst trigger keys on cfg.max_pods.  Wave 2
        # stays strictly below the 2*max_pods burst trigger so the
        # per-batch program compiles too; the burst wave is skipped
        # when the queue can never hold two batches (burst then never
        # engages in the measured run either).
        waves = []
        if (eff_multicycle > 1
                and cfg.queue_capacity >= eff_multicycle * cfg.max_pods):
            # Multicycle wave first: K batches compile the padded
            # K*cap window scan (the branch triggers at >= 2 batches
            # and pops up to K of them).
            waves.append(eff_multicycle * cfg.max_pods)
        if (wloop.burst_batches > 1
                and cfg.queue_capacity >= 2 * cfg.max_pods):
            waves.append(2 * cfg.max_pods)
        waves.append(min(cfg.max_pods, 8))
        for i, n_warm in enumerate(waves):
            warm = generate_workload(
                WorkloadSpec(num_pods=n_warm, seed=seed + 99 + i),
                scheduler_name=cfg.scheduler_name)
            wloop.client.add_pods(warm)
            wloop.run_until_drained()

    churn_tick = None
    if churn_links > 0:
        churn_tick = _churn_fn(
            loop.encoder, [n.name for n in cluster.list_nodes()],
            np.random.default_rng(seed + 13), churn_links)
        if warmup:
            _warm_churn_path(loop, churn_tick)
    if sampler is not None:
        sampler.start()
    start = time.perf_counter()
    cluster.add_pods(pods)
    if churn_tick is not None:
        _drain_with_churn(loop, churn_tick)
    else:
        loop.run_until_drained()
    if pipelined or auto_async_bind:
        # Bind confirmations land on the worker; the drain above
        # already flushed, but make the completion explicit so wall
        # covers every bind.
        loop.flush_binds()
    wall = time.perf_counter() - start
    # Quiesce the background refresher (off the timed window — its
    # whole point is to be off the critical path) so the refresh
    # counters below are final.
    loop.stop_static_refresher()

    bound = loop.scheduled
    return DensityResult(
        num_nodes=num_nodes,
        pods_submitted=len(pods),
        pods_bound=bound,
        pods_unschedulable=loop.unschedulable,
        wall_s=wall,
        pods_per_sec=bound / wall if wall > 0 else 0.0,
        score_p50_ms=loop.timer.percentile("score_assign", 50) * 1e3,
        score_p99_ms=loop.timer.percentile("score_assign", 99) * 1e3,
        encode_p99_ms=loop.timer.percentile("encode", 99) * 1e3,
        bind_p99_ms=loop.timer.percentile("bind", 99) * 1e3,
        score_samples=loop.timer.count("score_assign"),
        pipeline_budgets=loop.timer.pipeline_budgets(),
        bind_rtt_p99_ms=loop.timer.percentile("bind", 99) * 1e3,
        bind_retry_count=int(loop.bind_failures),
        staleness_bound_s=float(cfg.static_max_staleness_s),
        **_static_stats(loop),
        **_flight_stats(loop, trace_out),
        **_multicycle_stats(loop, cfg),
    )


def _run_density_device(cluster, loop: SchedulerLoop, pods, cfg,
                        method: str, num_nodes: int, seed: int,
                        warmup: bool, sampler=None,
                        chunk_batches: int = 2,
                        pipeline: bool = False,
                        mesh=None,
                        churn_links: int = 0,
                        trace_out: str | None = None) -> DensityResult:
    """Device-resident drain, two strategies sharing one harness.

    ``pipeline=False`` — whole-workload replay: ONE dispatch, one
    fetch, then a synchronous bind pass.  The minimum-dispatch shape;
    fastest when per-dispatch latency is high (tunneled chips).

    ``pipeline=True`` — chunked replay with an async bind worker: all
    chunks dispatched ahead through a bounded window (the scan carry
    threads the dependency),
    each chunk's assignments bound while the device runs later chunks —
    the async binding-cycle shape kube-scheduler itself uses, vs the
    reference's fully synchronous cycle (scheduler.go:189-237).  Wins
    when per-dispatch latency is low.

    The timed window covers everything a serving deployment does per
    pod — host encode of the stream, the device replay, and the host
    bind pass (fake API-server bookkeeping + events) — so host- and
    device-mode ``pods_per_sec`` are comparable.  Excluded: compilation
    (warmup) and the initial bulk host→device copy of the ``N×N``
    matrices (paid once at startup in a live deployment, then amortized
    via dirty-group updates).

    Score-latency reporting: in pipeline mode, every chunk arrival is
    host-timed (the blocking fetch of its assignment) and the
    percentiles are TRUE percentiles over those per-batch-normalized
    samples — one sample per chunk, so ``num_batches / chunk_batches``
    samples total (chunk_batches=2 at the bench's 64 batches gives 32).
    In monolithic device mode there is a single dispatch, so per-batch
    latency is the amortized mean (p50 == p99, score_samples == 1 —
    honestly labeled, not a percentile).  ``bind_p99_ms`` in pipeline
    mode is the bind worker's residual tail after the last fetch (the
    part the pipeline failed to hide)."""
    import queue as queue_mod
    import threading

    from kubernetesnetawarescheduler_tpu.core.replay import (
        pad_stream,
        replay_stream,
        replay_stream_pipelined,
        replay_stream_pipelined_feed,
    )

    cluster.add_pods(pods)
    queued = loop.queue.pop_batch(len(pods), timeout=0.0)
    num_batches = _round_up(len(queued), cfg.max_pods) // cfg.max_pods

    if mesh is not None and pipeline:
        # The chunked pipelined drain has no mesh variant (its
        # _replay_chunk dispatches aren't wrapped for GSPMD).  The
        # CALLER picks the drain (bench.py demotes to "device" and
        # reports what actually ran); silently switching here would
        # let its emitted mode label lie.
        raise ValueError(
            "mesh-sharded replay has no pipelined drain; use "
            "mode='device' with mesh")

    # The measured state is uploaded BEFORE the warmup so compilation
    # reuses the same device buffers: a second throwaway-cluster
    # snapshot would re-upload another ~2·N²·4 B of lat/bw (~210 MB at
    # N=5120) — minutes of wall-clock on a tunneled chip for arrays
    # whose only job is to carry compile shapes the measured state
    # already has.  The upload sits outside the timed window either
    # way (a live deployment pays it once at startup).
    state = loop.encoder.snapshot()
    import jax

    if mesh is not None:
        # Mesh path: place the state under the canonical shardings
        # HERE (outside the timed window, like the single-chip upload
        # above) and compile ONE jitted replay reused by warmup and
        # the measured run — sharded_replay_stream's per-call
        # jit+device_put would otherwise recompile and re-shard the
        # N×N matrices inside the window.
        from kubernetesnetawarescheduler_tpu.core.replay import (
            fold_stream,
        )
        from kubernetesnetawarescheduler_tpu.parallel.sharding import (
            _fold_spec,
            sharded_replay_fn,
            state_sharding,
        )

        state = jax.device_put(state, state_sharding(mesh))

        def _mesh_folded(stream_in):
            folded = fold_stream(stream_in, cfg)
            return jax.device_put(
                folded,
                jax.tree_util.tree_map(_fold_spec(mesh), folded))

        mesh_replay = [None]  # built on first use (warmup when on)

        def _mesh_run(stream_in):
            folded = _mesh_folded(stream_in)
            if mesh_replay[0] is None:
                mesh_replay[0] = sharded_replay_fn(cfg, mesh, method,
                                                   folded)
            return mesh_replay[0](state, folded)

    jax.block_until_ready(state)

    # Seeded churn (one tick per chunk arrival / bind batch): routes
    # fresh probe results through the serving loop's own
    # snapshot/_static_for path concurrently with the device drain, so
    # the run measures delta ingest + incremental refresh under load.
    # Assignments are unaffected — the replay consumes the state
    # uploaded above.
    churn_tick = None
    if churn_links > 0:
        churn_tick = _churn_fn(
            loop.encoder, [n.name for n in cluster.list_nodes()],
            np.random.default_rng(seed + 13), churn_links)

    def _churn_refresh():
        churn_tick()
        st, ver = loop.encoder.snapshot_versioned()
        loop._static_for(st, ver)

    if warmup:
        # Warm the host encode path against a throwaway ENCODER (so
        # the measured encode is warm Python, not first-touch
        # imports), but compile the replay on the measured state.
        wloop = _throwaway_loop(num_nodes, seed, cfg, method)
        wstream = pad_stream(
            wloop.encoder.encode_stream(queued, node_of=lambda name: ""),
            cfg.max_pods)
        # Also warm the MEASURED encoder's constraint-shape cache: a
        # long-running daemon serves with it warm (shapes are per
        # service/Deployment), so the timed encode should measure
        # steady state, not first-sight interning.
        loop.encoder.encode_stream(queued, node_of=lambda name: "")
        if pipeline and _overlap_encode():
            # Warm the FEED path (its jitted chunk fn is distinct from
            # the whole-stream variant's) over the same chunk-length
            # sequence the measured run will dispatch.
            cp = chunk_batches * cfg.max_pods
            for _ in replay_stream_pipelined_feed(
                    state, _stream_chunks(wstream, cp),
                    wstream.num_pods, cfg, method):
                pass
        elif pipeline:
            for _ in replay_stream_pipelined(state, wstream, cfg,
                                             method, chunk_batches):
                pass
        elif mesh is not None:
            wassign, _ = _mesh_run(wstream)
            np.asarray(wassign)
        else:
            wassign, _, _ = replay_stream(state, wstream, cfg, method,
                                          with_stats=True)
            np.asarray(wassign)
        if churn_tick is not None:
            _warm_churn_path(loop, churn_tick)
    if sampler is not None:
        sampler.start()

    work: queue_mod.Queue = queue_mod.Queue()
    bound_total = [0]
    binder_error: list[BaseException] = []
    # Per-batch bind latency samples from the bind stage itself.
    # bind_p99_ms is the percentile over THESE — the cost of one
    # batch's bind fanout where it actually runs (overlapped with the
    # device drain in pipeline mode) — not the wall residual after the
    # last fetch, which r5 reported as "bind_p99_ms" (905.74 ms at
    # N=5120: almost entirely drain serialization, not bind work).
    bind_times: list[float] = []
    # Bind-tail split: time each chunk's assignment sat in the work
    # queue before the binder picked it up, and the un-normalized wall
    # of each _bind_all round-trip (the "client RTT" share).
    queue_waits: list[float] = []
    rtt_times: list[float] = []

    def binder():
        while True:
            item = work.get()
            if item is None:
                return
            t_enq, chunk_pods, assignment = item
            queue_waits.append(time.perf_counter() - t_enq)
            try:
                tb = time.perf_counter()
                bound_total[0] += loop._bind_all(chunk_pods, assignment)
                rtt = time.perf_counter() - tb
                rtt_times.append(rtt)
                per_batch = max(1, -(-len(chunk_pods) // cfg.max_pods))
                bind_times.append(rtt / per_batch)
            except BaseException as exc:  # noqa: BLE001 — re-raised
                # after join: a dead binder must fail the benchmark,
                # not silently understate pods_bound.
                binder_error.append(exc)
                return

    t = None
    if pipeline:
        t = threading.Thread(target=binder, daemon=True)
        t.start()

    overlap = pipeline and _overlap_encode()
    enc_thread = None
    enc_secs = [0.0]
    start = time.perf_counter()
    if overlap:
        # Encode on a PRODUCER thread, chunk by chunk, while the
        # device drains earlier chunks: wall collapses from
        # encode + replay to max(encode, replay).  The producer runs
        # a single encoder pass (global peer index space, first-pod-
        # escape continuity — Encoder.encode_stream_chunks), the lock
        # released between chunks so the binder's commit_many
        # interleaves.
        chunk_pods_n = chunk_batches * cfg.max_pods
        s_total = _round_up(len(queued), cfg.max_pods)
        enc_q: queue_mod.Queue = queue_mod.Queue(maxsize=4)
        enc_err: list[BaseException] = []

        def producer():
            try:
                t_prev = time.perf_counter()
                for ch in loop.encoder.encode_stream_chunks(
                        queued, node_of=loop._peer_node,
                        chunk_pods=chunk_pods_n):
                    # Accumulate encode time only (exclude the
                    # backpressure wait in put()).
                    enc_secs[0] += time.perf_counter() - t_prev
                    enc_q.put(pad_stream(ch, cfg.max_pods))
                    t_prev = time.perf_counter()
            except BaseException as exc:  # noqa: BLE001 — re-raised
                # by the consumer; a dead producer must fail the
                # benchmark, not hang the drain.
                enc_err.append(exc)
            finally:
                enc_q.put(None)

        def _q_chunks():
            while True:
                ch = enc_q.get()
                if ch is None:
                    if enc_err:
                        raise enc_err[0]
                    return
                yield ch

        enc_thread = threading.Thread(target=producer, daemon=True)
        enc_thread.start()
        encode_wall = 0.0  # overlapped — not a serial wall segment
    else:
        stream = pad_stream(
            loop.encoder.encode_stream(queued, node_of=loop._peer_node),
            cfg.max_pods)
        encode_wall = time.perf_counter() - start
        enc_secs[0] = encode_wall

    chunk_times: list[float] = []
    round_samples: list[int] = []
    if pipeline:
        # Eager setup (static prep + stream upload + window dispatch)
        # happens inside this CALL — after it, per-chunk samples time
        # chunk service only; the setup still lands in the throughput
        # wall above.
        if overlap:
            chunks = replay_stream_pipelined_feed(
                state, _q_chunks(), s_total, cfg, method)
        else:
            chunks = replay_stream_pipelined(state, stream, cfg, method,
                                             chunk_batches)
        chunk_iter = iter(chunks)
        prev = time.perf_counter()
        while True:
            # One flight-recorder span per chunk arrival: the bench
            # drain leaves the same decision-level trace a serving
            # deployment would, so --trace-out and the artifact's
            # trace_provenance block work in the headline pipeline
            # mode too (path "bench_chunk", device_wait = the blocking
            # fetch this mode's score percentiles are built from).
            sb = loop._span_begin("bench_chunk")
            try:
                with sb.phase("device_wait"):
                    pod_start, assignment, rounds = next(chunk_iter)
            except StopIteration:
                break
            round_samples.extend(int(r) for r in rounds)
            now = time.perf_counter()
            # Host-observed latency of this chunk (blocking fetch),
            # normalized per batch: a true sample, not an average over
            # the whole run.
            batches_in_chunk = max(1, len(assignment) // cfg.max_pods)
            chunk_times.append((now - prev) / batches_in_chunk)
            prev = now
            end = min(pod_start + len(assignment), len(queued))
            chunk_pods = queued[pod_start:end]
            if pod_start < end:
                work.put((time.perf_counter(), chunk_pods,
                          assignment[:end - pod_start]))
            if churn_tick is not None:
                # Host-side ingest + refresh handoff between fetches —
                # lands in the next chunk sample, exactly where a
                # serving cycle pays it.
                with sb.phase("ingest"):
                    _churn_refresh()
            loop._span_commit(sb, chunk_pods)
        device_span = time.perf_counter() - start - encode_wall
        work.put(None)
        t.join()
        if enc_thread is not None:
            enc_thread.join()
        if binder_error:
            raise binder_error[0]
        bound = bound_total[0]
    else:
        # Monolithic replay = one serving "cycle" in the recorder: one
        # device_wait phase (the whole-workload dispatch+fetch) and one
        # bind phase covering the per-batch bind pass.
        sb = loop._span_begin("bench_device")
        t_dev = time.perf_counter()
        if mesh is not None:
            assignment_dev, _final = _mesh_run(stream)
        else:
            assignment_dev, _final, rounds_dev = replay_stream(
                state, stream, cfg, method, with_stats=True)
            round_samples.extend(int(r) for r in np.asarray(rounds_dev))
        assignment = np.asarray(assignment_dev)[:len(queued)]
        sb.add_phase("device_wait", t_dev,
                     time.perf_counter() - t_dev)
        device_span = time.perf_counter() - start - encode_wall
        # Per-batch bind pass, sampled per batch — same fanout, real
        # percentiles instead of one monolithic wall.
        bound = 0
        t_bind = time.perf_counter()
        for a in range(0, len(queued), cfg.max_pods):
            tb = time.perf_counter()
            bound += loop._bind_all(queued[a:a + cfg.max_pods],
                                    assignment[a:a + cfg.max_pods])
            rtt = time.perf_counter() - tb
            bind_times.append(rtt)
            rtt_times.append(rtt)
            if churn_tick is not None:
                _churn_refresh()
        sb.add_phase("bind", t_bind, time.perf_counter() - t_bind)
        loop._span_commit(sb, queued)
    wall = time.perf_counter() - start
    # Quiesce the background refresher off the timed window so the
    # refresh counters below are final.
    loop.stop_static_refresher()

    if chunk_times:
        score_p50 = _percentile_ms(chunk_times, 50)
        score_p99 = _percentile_ms(chunk_times, 99)
        samples = len(chunk_times)
    else:
        amortized_ms = device_span / max(num_batches, 1) * 1e3
        score_p50 = score_p99 = amortized_ms
        samples = 1
    return DensityResult(
        num_nodes=num_nodes,
        pods_submitted=len(pods),
        pods_bound=bound,
        pods_unschedulable=loop.unschedulable,
        wall_s=wall,
        pods_per_sec=bound / wall if wall > 0 else 0.0,
        score_p50_ms=score_p50,
        score_p99_ms=score_p99,
        encode_p99_ms=enc_secs[0] / max(num_batches, 1) * 1e3,
        bind_p99_ms=_percentile_ms(bind_times, 99),
        score_samples=samples,
        rounds_p50=_percentile(round_samples, 50),
        rounds_p99=_percentile(round_samples, 99),
        rounds_max=max(round_samples, default=0),
        bind_tail_ms=round(
            max(0.0, wall - device_span - encode_wall) * 1e3, 3),
        bind_queue_wait_p99_ms=round(
            _percentile_ms(queue_waits, 99), 3),
        bind_rtt_p99_ms=round(_percentile_ms(rtt_times, 99), 3),
        bind_retry_count=int(loop.bind_failures),
        staleness_bound_s=float(cfg.static_max_staleness_s),
        **_static_stats(loop),
        **_flight_stats(loop, trace_out),
        **_multicycle_stats(loop, cfg),
    )


def measure_device_latency(num_nodes: int, batch_size: int,
                           score_backend: str = "pallas",
                           reps: int = 50, seed: int = 7,
                           warmup_reps: int = 3,
                           scan_k: int = 32,
                           fusion_ab: bool = True) -> dict:
    """SCAN-AMORTIZED per-batch device latency of ``schedule_batch``
    (score + conflict resolution + commit — the full per-batch
    scheduling decision): ``scan_k`` chained steps inside ONE jitted
    ``lax.scan`` dispatch, wall divided by ``scan_k``; percentiles
    over ``reps`` such dispatches.

    This is the north star's "p99 Score() < 5 ms" measured where the
    bar means it — ON DEVICE.  Each scan step's commit feeds the next
    step's state (the replay's own carry threading), so XLA cannot
    elide work, and the per-DISPATCH overheads — Python dispatch, the
    runtime's launch path, and on a remote-attached chip the transport
    round-trip — amortize to 1/``scan_k`` of one step.  Round 5
    carried two contradictory "device" p99s for the same program
    (87.44 ms in BENCH_r05 vs 3.35 ms in device_latency.json) because
    one path re-uploaded host-resident inputs through a ~65 ms tunnel
    every rep; the scan shape makes that class of error structurally
    impossible — a K-step chain with host inputs would read as K
    uploads, not one kernel (root cause: docs/ROUND_NOTES.md r6).
    ``block_until_ready`` on the device-resident final carry — no bulk
    device→host transfer inside the timed window.

    The scanned step is the SERVING LOOP's cache-hit per-batch
    dispatch: ``assign_parallel`` with the precomputed batch-invariant
    static (SchedulerLoop._static_for amortizes the O(N²) normalizer
    prep across cycles until metrics/network move) plus
    ``commit_assignments``.  The one-off prep cost is reported
    separately as ``static_prep_ms``.

    Returns a dict (not a DensityResult): this is a microbenchmark of
    the per-batch decision, not a drain.  ``p99_source`` is
    ``"device_scan_amortized"`` — the single methodology label
    tools/bench_check.py enforces across every committed artifact."""
    import jax

    from kubernetesnetawarescheduler_tpu.core.assign import (
        assign_parallel,
        commit_assignments,
    )
    from kubernetesnetawarescheduler_tpu.core.pallas_score import (
        compute_assign_static,
    )

    cfg = SchedulerConfig(max_nodes=_round_up(num_nodes, 128),
                          max_pods=batch_size, max_peers=4,
                          score_backend=score_backend)
    loop = _throwaway_loop(num_nodes, seed, cfg, "parallel")
    pods = generate_workload(
        WorkloadSpec(num_pods=batch_size, seed=seed + 5, services=8,
                     peer_fraction=0.5, affinity_fraction=0.1,
                     anti_fraction=0.1),
        scheduler_name=cfg.scheduler_name)
    batch = loop.encoder.encode_pods(pods, node_of=lambda n: "",
                                     lenient=True)
    state = loop.encoder.snapshot()
    prep = jax.jit(lambda s: compute_assign_static(s, cfg))
    static = jax.block_until_ready(prep(state))  # compile
    t0 = time.perf_counter()
    static = jax.block_until_ready(prep(state))
    static_prep_ms = (time.perf_counter() - t0) * 1e3

    scan_k = max(1, int(scan_k))

    def _chain(s, b, st):
        # The SAME batch re-scored every step against the evolving
        # state: each commit mutates used/group_bits/…, which the next
        # step's scoring reads — a real data dependency per step, the
        # exact carry threading core/replay.py's _make_step uses.
        def body(carry, _):
            a = assign_parallel(carry, b, cfg, st)
            return commit_assignments(carry, b, a), a.sum()

        final, checks = jax.lax.scan(body, s, None, length=scan_k)
        return final, checks

    # Device-resident inputs, put ONCE before the timing loop:
    # ``snapshot()``/``encode_pods`` return HOST numpy, and without an
    # explicit put the first dispatch re-uploads the full N-node
    # snapshot (tens of MB at N=5120).
    state = jax.device_put(state)
    batch = jax.device_put(batch)
    static = jax.device_put(static)
    step = jax.jit(_chain)
    for _ in range(max(1, warmup_reps)):
        jax.block_until_ready(step(state, batch, static))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(step(state, batch, static))
        # One sample = per-step latency with dispatch/transport
        # amortized across the chain.
        times.append((time.perf_counter() - t0) / scan_k)
    winner_fusion = (_fusion_ab_leg(state, batch, static, cfg, scan_k)
                     if fusion_ab else None)
    out = {
        "p50_ms": round(_percentile_ms(times, 50), 3),
        "p99_ms": round(_percentile_ms(times, 99), 3),
        "max_ms": round(max(times) * 1e3, 3),
        "reps": len(times),
        "scan_k": scan_k,
        "static_prep_ms": round(static_prep_ms, 3),
        "num_nodes": num_nodes,
        "batch_size": batch_size,
        "score_backend": score_backend,
        "backend": jax.default_backend(),
        # THE one timing methodology, named: K chained steps in one
        # jitted lax.scan, block_until_ready on the device-resident
        # final carry, wall / K per sample.
        "p99_source": "device_scan_amortized",
    }
    if winner_fusion is not None:
        out["winner_fusion"] = winner_fusion
    return out


def _fusion_ab_leg(state, batch, static, cfg, scan_k: int) -> dict:
    """Fused-vs-unfused A/B at the PER-DISPATCH seam (ISSUE 9,
    bench_check Rule 9's ``winner_fusion`` provenance block).

    The committed serving step before r9 was TWO top-level dispatches
    per batch — ``assign_parallel`` then ``commit_assignments`` with
    host threading between them and no donation;
    :func:`~..core.assign.fused_schedule_step` is ONE dispatch with
    the state buffers donated.  Both legs chain ``scan_k`` per-batch
    steps on an OWNED copy of the state (the donation contract:
    fused_schedule_step invalidates its input) and time each step's
    wall individually — per-DISPATCH, because dispatch count and
    copy elision are exactly what fusion changes; the artifact's
    headline p99 stays the scan-amortized methodology and is reported
    separately.  Donation is verified, not assumed: after every fused
    step the previous carry's ``used`` buffer must read as deleted
    (XLA consumed or forwarded it) — a live buffer counts as a
    ``donation_failure``.  The rounds histogram comes from the fused
    leg's ``with_stats`` round counts (same observable as
    ``rounds_p50/p99`` in the drain)."""
    import jax
    import jax.numpy as jnp

    from kubernetesnetawarescheduler_tpu.core.assign import (
        assign_parallel,
        commit_assignments,
        fused_schedule_step,
    )

    commit_j = jax.jit(commit_assignments)
    warm = 2

    def _unfused_leg():
        s = jax.tree.map(jnp.array, state)
        samples, rounds = [], []
        for i in range(scan_k + warm):
            t0 = time.perf_counter()
            a, r = assign_parallel(s, batch, cfg, static,
                                   with_stats=True)
            s = commit_j(s, batch, a)
            jax.block_until_ready(s)
            dt = time.perf_counter() - t0
            if i >= warm:
                samples.append(dt)
                rounds.append(int(r))
        return samples, rounds

    def _fused_leg():
        s = jax.tree.map(jnp.array, state)
        samples, rounds = [], []
        donated = failures = 0
        for i in range(scan_k + warm):
            prev_used = s.used
            t0 = time.perf_counter()
            s, a, r = fused_schedule_step(s, batch, cfg, static)
            jax.block_until_ready(s)
            dt = time.perf_counter() - t0
            if prev_used.is_deleted():
                donated += 1
            else:
                failures += 1
            if i >= warm:
                samples.append(dt)
                rounds.append(int(r))
        return samples, rounds, donated, failures

    fu_samples, fu_rounds, donated, failures = _fused_leg()
    un_samples, _un_rounds = _unfused_leg()
    return {
        "enabled": bool(getattr(cfg, "enable_winner_fusion", False)),
        "donated": int(donated),
        "donation_failures": int(failures),
        "rounds": {
            "p50": _percentile(fu_rounds, 50),
            "p99": _percentile(fu_rounds, 99),
            "max": int(max(fu_rounds, default=0)),
        },
        "fused_step_p50_ms": round(_percentile_ms(fu_samples, 50), 3),
        "fused_step_p99_ms": round(_percentile_ms(fu_samples, 99), 3),
        "unfused_step_p50_ms": round(_percentile_ms(un_samples, 50),
                                     3),
        "unfused_step_p99_ms": round(_percentile_ms(un_samples, 99),
                                     3),
        "steps_per_leg": int(scan_k),
        # A/B methodology marker: per-dispatch wall of a Python-chained
        # K-step sequence (NOT scan-amortized — the dispatch overhead
        # is part of what the A/B measures).
        "ab_source": "per_dispatch_chain",
    }

def measure_multicycle_latency(num_nodes: int, batch_size: int,
                               k: int = 8,
                               score_backend: str = "pallas",
                               reps: int = 30, seed: int = 7,
                               warmup_reps: int = 3) -> dict:
    """DEVICE-BOUNDARY per-cycle latency of the persistent multi-cycle
    window (ISSUE 17): one ``replay_stream_static`` dispatch over a
    K-wave device-resident window — the serving loop's exact
    multicycle program — followed by ONE assignments fetch to host,
    wall divided by ``k``; percentiles over ``reps`` such windows.

    This is the number the r5 gap was about: BENCH_r05's 87 ms
    "score_p99_ms" was a per-cycle dispatch+fetch at the device
    boundary, while the 5 ms bar was only met in-kernel
    (scan-amortized).  The multi-cycle window closes it structurally —
    K logical cycles share one dispatch and one fetch, so the
    boundary overheads (Python dispatch, runtime launch, transport,
    device→host assignment readback) amortize to 1/K per cycle while
    the commit→score carry threading keeps placements bit-identical
    to K sequential per-batch steps.

    ``p99_source`` is ``"device_boundary_multicycle"`` — AMORTIZED at
    the boundary, accepted by bench_check Rule 16 (unlike the
    unamortized ``"device_boundary"`` label, which Rule 16 makes
    fatal beside a p99_met claim).  The ``scan_reference`` block
    carries the in-kernel scan-amortized p99 from the same build so
    the artifact shows the boundary-vs-kernel ratio on its face."""
    import jax

    from kubernetesnetawarescheduler_tpu.core.replay import (
        pad_stream,
        replay_stream_static,
    )

    k = max(1, int(k))
    cfg = SchedulerConfig(max_nodes=_round_up(num_nodes, 128),
                          max_pods=batch_size, max_peers=4,
                          score_backend=score_backend,
                          multicycle=k)
    loop = _throwaway_loop(num_nodes, seed, cfg, "parallel",
                           multicycle=k)
    pods = generate_workload(
        WorkloadSpec(num_pods=k * batch_size, seed=seed + 5,
                     services=8, peer_fraction=0.5,
                     affinity_fraction=0.1, anti_fraction=0.1),
        scheduler_name=cfg.scheduler_name)
    stream = loop.encoder.encode_stream(pods, node_of=lambda n: "",
                                        lenient=True)
    stream = pad_stream(stream, k * batch_size)
    state = loop.encoder.snapshot()
    static = loop._static_for(state, 0)
    # Window staged device-resident ONCE (the DeviceWaveRing's job in
    # serving); the timed window then pays exactly what a retire pays:
    # one dispatch + one host readback of the K*cap assignments.
    state = jax.device_put(state)
    stream = jax.device_put(stream)
    static = jax.device_put(static)

    def _window():
        t0 = time.perf_counter()
        a, _final, _r = replay_stream_static(
            state, stream, static, cfg, "parallel", with_stats=True)
        np.asarray(a)  # the retire-seam device->host fetch
        return (time.perf_counter() - t0) / k

    for _ in range(max(1, warmup_reps)):
        _window()
    times = [_window() for _ in range(reps)]
    return {
        "p50_ms": round(_percentile_ms(times, 50), 3),
        "p99_ms": round(_percentile_ms(times, 99), 3),
        "max_ms": round(max(times) * 1e3, 3),
        "reps": len(times),
        "multicycle_k": k,
        "num_nodes": num_nodes,
        "batch_size": batch_size,
        "score_backend": score_backend,
        "backend": jax.default_backend(),
        # Methodology marker: K logical cycles per dispatch, ONE
        # device->host assignments fetch, wall / K per sample —
        # measured AT the device boundary, amortized by the window.
        "p99_source": "device_boundary_multicycle",
    }

def multicycle_identity_check(num_nodes: int = 128,
                              batch_size: int = 16,
                              k: int = 8,
                              coalesce: int = 4,
                              inflight: int = 2,
                              num_pods: int = 192,
                              seed: int = 11) -> dict:
    """Placement bit-identity A/B for the r16 serving path: the SAME
    seeded workload drained by (a) K=1 with coalescing off — exactly
    the r15 per-cycle path, the multicycle branch never fires — and
    (b) multicycle K with coalesced async binds.  Returns the
    per-pod-placement comparison the bench artifact publishes under
    ``detail.multicycle.identity_ab`` (bench_check Rule 16): the 5 ms
    chase is only a perf claim if the amortized program provably
    changes NOTHING about where pods land."""
    def _drain(mc: int, co: int, infl: int) -> dict:
        cfg = SchedulerConfig(
            max_nodes=_round_up(num_nodes, 128),
            max_pods=batch_size, max_peers=4,
            queue_capacity=max(300, num_pods + batch_size,
                               mc * batch_size),
            multicycle=mc,
            bind_coalesce_window=co,
            bind_max_inflight=infl)
        cluster, lat, bw = build_fake_cluster(
            ClusterSpec(num_nodes=num_nodes, seed=seed))
        loop = SchedulerLoop(cluster, cfg, method="parallel",
                             async_bind=(co > 1 or infl > 1),
                             multicycle=mc)
        loop.encoder.set_network(lat, bw)
        feed_metrics(cluster, loop.encoder,
                     np.random.default_rng(seed + 1))
        pods = generate_workload(
            WorkloadSpec(num_pods=num_pods, seed=seed + 2),
            scheduler_name=cfg.scheduler_name)
        cluster.add_pods(pods)
        loop.run_until_drained()
        loop.flush_binds()
        loop.stop_bind_worker()
        return {b.pod_name: b.node_name for b in cluster.bindings}

    base = _drain(1, 1, 1)
    multi = _drain(max(2, k), max(1, coalesce), max(1, inflight))
    return {
        "identical": multi == base,
        "k": int(max(2, k)),
        "coalesce_window": int(max(1, coalesce)),
        "max_inflight": int(max(1, inflight)),
        "pods_compared": len(base),
        "baseline": "k1_coalescing_off_r15_path",
    }
