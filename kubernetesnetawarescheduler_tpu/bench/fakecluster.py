"""Fake cluster generation: nodes, hierarchical network, workloads.

SURVEY.md 4(b): the replacement for the reference's live 5-node edge
cluster (hardcoded IPs scheduler.go:275-279, node names :252-256).  A
generated cluster has:

- heterogeneous nodes across zones and racks (the reference's analog:
  one x86 master + four Raspberry Pis);
- a hierarchical network model: same-rack links are fast/near,
  cross-rack slower, cross-zone slowest — producing the ``lat``/``bw``
  matrices the probe pipeline would measure (netperfScript/run.sh);
- node_exporter-shaped metric samples;
- workloads of services whose pods exchange traffic (peers), with
  optional affinity/anti-affinity groups — the pod-aware dimension the
  reference never modeled.

Also provides fault injection (drop/corrupt/stale metric updates) for
the failure-handling tests (SURVEY.md 5).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Sequence

import numpy as np

from kubernetesnetawarescheduler_tpu.config import Metric
from kubernetesnetawarescheduler_tpu.k8s.client import FakeCluster
from kubernetesnetawarescheduler_tpu.k8s.types import Node, Pod


@dataclasses.dataclass(frozen=True)
class NodeClassSpec:
    """One heterogeneous node class (the VirtualFlow-style hardware
    decoupling, PAPERS.md): capacity ranges override the ClusterSpec
    defaults and the link scales shift every link touching a node of
    this class (a slow NIC bounds the link, so a pair's latency takes
    the WORSE class's scale and its bandwidth the SMALLER one).

    ``fraction`` is the class's share of the fleet; classes partition
    the node index range deterministically (largest-first by spec
    order), so the assignment never consumes generator randomness and
    the single-class default stays bit-identical."""

    name: str
    fraction: float
    cpu_range: tuple[float, float] | None = None
    mem_range: tuple[float, float] | None = None
    netbw_range: tuple[float, float] | None = None
    lat_scale: float = 1.0   # multiplies latencies on the node's links
    bw_scale: float = 1.0    # multiplies bandwidths on the node's links


@dataclasses.dataclass(frozen=True)
class ClusterSpec:
    """Shape of a generated cluster."""

    num_nodes: int = 100
    zones: int = 2
    racks_per_zone: int = 4
    seed: int = 0

    # Heterogeneous node classes; () = today's single-class fleet
    # (the default MUST stay bit-identical: the class path is gated,
    # pinned by tests/test_scenario.py::test_fakecluster_default_parity).
    node_classes: tuple[NodeClassSpec, ...] = ()

    # Link model (lat ms / bw bits-per-sec) by proximity tier.
    lat_same_rack: float = 0.1
    lat_same_zone: float = 0.5
    lat_cross_zone: float = 2.0
    bw_same_rack: float = 25e9
    bw_same_zone: float = 10e9
    bw_cross_zone: float = 1e9
    jitter: float = 0.15  # multiplicative noise on links

    # Node capacity ranges (cpu cores, mem GiB, net Gbps).
    cpu_range: tuple[float, float] = (8.0, 64.0)
    mem_range: tuple[float, float] = (16.0, 256.0)
    netbw_range: tuple[float, float] = (10.0, 40.0)

    taint_fraction: float = 0.05  # nodes tainted "dedicated"


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """Shape of a generated pod workload."""

    num_pods: int = 300
    services: int = 20           # pods are grouped into services
    peer_fraction: float = 0.6   # fraction of pods with traffic peers
    max_peers: int = 4
    affinity_fraction: float = 0.1
    anti_fraction: float = 0.1
    tolerate_fraction: float = 0.05
    # Preferred (soft) affinity: fraction of pods carrying a weighted
    # zone preference (``soft_node_affinity`` toward a random zone
    # label) / a weighted spread preference away from their own
    # service's group (negative ``soft_group_affinity``).
    soft_zone_fraction: float = 0.0
    soft_spread_fraction: float = 0.0
    # Topology spread: fraction of pods carrying a zone-level
    # topologySpreadConstraint on their service's group (maxSkew 1-2;
    # hard_fraction of those are DoNotSchedule, rest ScheduleAnyway).
    spread_fraction: float = 0.0
    spread_hard_fraction: float = 0.5
    # Zone-scoped hard pod (anti-)affinity: fraction of pods requiring
    # co-residency with their OWN service's group at zone granularity
    # (followers joining an established service), and fraction
    # declaring zone-anti against a random OTHER service.
    zone_aff_fraction: float = 0.0
    zone_anti_fraction: float = 0.0
    # Hard nodeAffinity matchExpressions: fraction of pods requiring
    # ``disk In [ssd]`` (half) or ``disk NotIn [hdd]`` (half) — the
    # fake cluster labels nodes disk=ssd/hdd alternately.
    ns_fraction: float = 0.0
    zones: int = 2  # must match the ClusterSpec the workload runs on
    seed: int = 0
    cpu_range: tuple[float, float] = (0.1, 4.0)
    mem_range: tuple[float, float] = (0.2, 8.0)
    netbw_range: tuple[float, float] = (0.05, 2.0)


def _assign_node_classes(spec: ClusterSpec
                         ) -> list[NodeClassSpec] | None:
    """Deterministic node-index -> class map (None when the spec has
    no classes).  Largest-remainder apportionment over contiguous
    index blocks: no generator randomness is consumed, so adding
    classes never perturbs the capacity/taint/jitter draw stream."""
    if not spec.node_classes:
        return None
    total = sum(c.fraction for c in spec.node_classes)
    if total <= 0:
        raise ValueError("node_classes fractions must sum > 0")
    n = spec.num_nodes
    quotas = [c.fraction / total * n for c in spec.node_classes]
    counts = [int(q) for q in quotas]
    remainders = sorted(range(len(quotas)),
                        key=lambda k: (quotas[k] - counts[k], -k),
                        reverse=True)
    for k in remainders[:n - sum(counts)]:
        counts[k] += 1
    out: list[NodeClassSpec] = []
    for cls, cnt in zip(spec.node_classes, counts):
        out.extend([cls] * cnt)
    return out


def build_fake_cluster(spec: ClusterSpec, client_cls=FakeCluster,
                       chaos=None,
                       **client_kw) -> tuple[FakeCluster, np.ndarray,
                                             np.ndarray]:
    """Create a populated :class:`FakeCluster` plus its ground-truth
    ``(lat_ms, bw_bps)`` matrices (what a perfect probe pipeline would
    measure).  ``client_cls``/``client_kw`` let tests swap in a
    fault-injecting subclass or an emulated API RTT
    (``bind_latency_s``).

    ``chaos`` wraps the populated cluster in a fault-injecting
    :class:`~kubernetesnetawarescheduler_tpu.k8s.chaos.ChaosKubeProxy`:
    pass a :class:`~kubernetesnetawarescheduler_tpu.k8s.chaos.ChaosSchedule`
    for full control, or an int seed to generate the default schedule.
    The returned client is then the proxy (its ``.inner`` is the bare
    cluster)."""
    rng = np.random.default_rng(spec.seed)
    cluster = client_cls(**client_kw)
    n = spec.num_nodes
    zones = np.arange(n) % spec.zones
    racks = (np.arange(n) // spec.zones) % spec.racks_per_zone
    classes = _assign_node_classes(spec)

    for i in range(n):
        cls = classes[i] if classes is not None else None
        cpu_range = spec.cpu_range
        mem_range = spec.mem_range
        netbw_range = spec.netbw_range
        extra: frozenset[str] = frozenset()
        if cls is not None:
            cpu_range = cls.cpu_range or cpu_range
            mem_range = cls.mem_range or mem_range
            netbw_range = cls.netbw_range or netbw_range
            extra = frozenset({f"nodeclass={cls.name}"})
        tainted = rng.random() < spec.taint_fraction
        cluster.add_node(Node(
            name=f"node-{i:04d}",
            capacity={
                "cpu": float(rng.uniform(*cpu_range)),
                "mem": float(rng.uniform(*mem_range)),
                "net_bw": float(rng.uniform(*netbw_range)),
            },
            labels=frozenset({f"zone={zones[i]}", f"rack={racks[i]}",
                              f"disk={'ssd' if i % 2 == 0 else 'hdd'}"})
            | extra,
            taints=frozenset({"dedicated"}) if tainted else frozenset(),
            zone=f"zone-{zones[i]}",
            rack=f"rack-{zones[i]}-{racks[i]}",
        ))

    same_zone = zones[:, None] == zones[None, :]
    same_rack = same_zone & (racks[:, None] == racks[None, :])
    lat = np.where(same_rack, spec.lat_same_rack,
                   np.where(same_zone, spec.lat_same_zone,
                            spec.lat_cross_zone)).astype(np.float32)
    bw = np.where(same_rack, spec.bw_same_rack,
                  np.where(same_zone, spec.bw_same_zone,
                           spec.bw_cross_zone)).astype(np.float32)
    noise = 1.0 + spec.jitter * rng.standard_normal((n, n)).astype(np.float32)
    noise = np.clip((noise + noise.T) / 2, 0.5, 1.5)
    lat = lat * noise
    bw = bw / noise
    if classes is not None:
        ls = np.array([classes[i].lat_scale for i in range(n)],
                      np.float32)
        bs = np.array([classes[i].bw_scale for i in range(n)],
                      np.float32)
        lat = lat * np.maximum.outer(ls, ls)
        bw = bw * np.minimum.outer(bs, bs)
    np.fill_diagonal(lat, 0.0)
    np.fill_diagonal(bw, bw.max())
    if chaos is not None:
        from kubernetesnetawarescheduler_tpu.k8s.chaos import (
            ChaosKubeProxy,
            ChaosSchedule,
        )
        schedule = (chaos if isinstance(chaos, ChaosSchedule)
                    else ChaosSchedule.generate(int(chaos)))
        cluster = ChaosKubeProxy(cluster, schedule)
    return cluster, lat, bw


def sample_metrics(rng: np.random.Generator) -> dict[str, float]:
    """One node_exporter-shaped sample (channels of config.Metric)."""
    return {
        "cpu_freq": float(rng.uniform(6e8, 2.4e9)),
        "mem_pct": float(rng.uniform(5.0, 95.0)),
        "net_tx": float(rng.uniform(1e4, 1e7)),
        "net_rx": float(rng.uniform(1e4, 1e7)),
        "bandwidth": float(rng.uniform(1e8, 1e10)),
        "disk_io": float(rng.integers(0, 16)),
    }


assert set(sample_metrics(np.random.default_rng(0))) == set(Metric.NAMES)


def feed_metrics(cluster: FakeCluster, encoder, rng: np.random.Generator,
                 drop_fraction: float = 0.0) -> None:
    """Push a metrics sample for every node into an Encoder; with
    ``drop_fraction`` > 0, some nodes are skipped (scrape failure) —
    their staleness keeps growing instead of crashing the scorer the
    way the reference does on a failed scrape (it ``println``s the
    error then dereferences the nil body, scheduler.go:397-405)."""
    for node in cluster.list_nodes():
        if drop_fraction and rng.random() < drop_fraction:
            continue
        encoder.update_metrics(node.name, sample_metrics(rng), age_s=0.0)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Fault-injection policy for the synthetic node_exporter fleet
    (SURVEY.md §5 failure-detection row: "a fault-injection mode in the
    fake cluster generator (drop/timeout/corrupt metric updates)").

    Fractions are per-scrape probabilities, independent per node.  The
    reference crashed on any of these: a failed ``http.Get`` left a nil
    body that was read anyway (scheduler.go:397-405); a corrupt body
    broke the fixed-offset substring slicing (scheduler.go:409-442)."""

    drop_fraction: float = 0.0      # connection refused (raises)
    timeout_fraction: float = 0.0   # request timeout (raises)
    corrupt_fraction: float = 0.0   # body is binary garbage
    nan_fraction: float = 0.0       # body parses but values are NaN/Inf
    dead_nodes: frozenset[str] = frozenset()  # never answer at all
    seed: int = 0


def synth_exporter_body(values: dict[str, float], num_cpus: int = 4,
                        nan: bool = False) -> str:
    """A node_exporter-format scrape body realizing the given metric
    channels (the inverse of
    :class:`~..ingest.prometheus.NodeExporterExtractor`)."""
    bad = "NaN"
    cpu = bad if nan else f"{values['cpu_freq']:.1f}"
    total = 16e9
    avail = bad if nan else f"{(100.0 - values['mem_pct']) / 100.0 * total:.0f}"
    tx = bad if nan else f"{values['net_tx']:.0f}"
    rx = bad if nan else f"{values['net_rx']:.0f}"
    disk = bad if nan else f"{values['disk_io']:.0f}"
    lines = ["# HELP node_cpu_scaling_frequency_hertz freq",
             "# TYPE node_cpu_scaling_frequency_hertz gauge"]
    for c in range(num_cpus):
        lines.append(
            f'node_cpu_scaling_frequency_hertz{{cpu="{c}"}} {cpu}')
    lines += [
        f"node_memory_MemTotal_bytes {total:.0f}",
        f"node_memory_MemAvailable_bytes {avail}",
        f'node_network_transmit_packets_total{{device="eth0"}} {tx}',
        f'node_network_receive_packets_total{{device="eth0"}} {rx}',
        f'node_network_transmit_packets_total{{device="flannel.1"}} 12345',
        f'node_disk_io_now{{device="sda"}} {disk}',
    ]
    return "\n".join(lines) + "\n"


class FaultyExporterFleet:
    """A ``fetch`` callable for :class:`~..ingest.scraper.ScrapePool`
    backed by synthetic per-node exporters with injected faults.

    Targets map node names to ``fake://<node-name>`` URLs."""

    def __init__(self, node_names: Sequence[str],
                 spec: FaultSpec = FaultSpec()) -> None:
        self.spec = spec
        self._rng = np.random.default_rng(spec.seed)
        # ScrapePool fetches from a thread pool; numpy Generators are
        # not thread-safe, so draws are serialized (the bodies are tiny
        # — the lock is not a bench bottleneck, this is a test double).
        self._lock = threading.Lock()
        self._names = list(node_names)
        self.calls = 0

    def targets(self) -> dict[str, str]:
        return {name: f"fake://{name}" for name in self._names}

    def fetch(self, url: str) -> str:
        assert url.startswith("fake://")
        name = url[len("fake://"):]
        with self._lock:
            return self._fetch_locked(name)

    def _fetch_locked(self, name: str) -> str:
        self.calls += 1
        spec, rng = self.spec, self._rng
        if name in spec.dead_nodes:
            raise ConnectionRefusedError(name)
        roll = rng.random()
        if roll < spec.drop_fraction:
            raise ConnectionRefusedError(name)
        if roll < spec.drop_fraction + spec.timeout_fraction:
            raise TimeoutError(name)
        if roll < (spec.drop_fraction + spec.timeout_fraction
                   + spec.corrupt_fraction):
            return "\x00\xff garbage {{{ not prometheus\n== 4 5 6"
        nan = roll < (spec.drop_fraction + spec.timeout_fraction
                      + spec.corrupt_fraction + spec.nan_fraction)
        return synth_exporter_body(sample_metrics(rng), nan=nan)


def generate_gang_workload(num_gangs: int = 12,
                           member_counts: Sequence[int] = (8, 16, 32),
                           filler_pods: int = 0,
                           seed: int = 0,
                           cpu: float = 7.0,
                           mem: float = 12.0,
                           netbw: float = 1.0,
                           scheduler_name: str = "netAwareScheduler"
                           ) -> list[Pod]:
    """TPU-slice-job shaped workload: ``num_gangs`` pod groups cycling
    through ``member_counts`` members each (the gang annotation
    contract, core/gang.py), plus ``filler_pods`` independent pods,
    interleaved so the gang gate actually absorbs partial groups.
    Members are homogeneous (one slice = identical workers) and
    node-sized — a real TPU slice runs ~one worker per host, so the
    defaults request enough cpu/mem that a gang CANNOT collapse onto
    one node and placement quality is decided by which rack/zone the
    members spread across — the regime the group objective exists
    for."""
    rng = np.random.default_rng(seed)
    pods: list[Pod] = []
    for g in range(num_gangs):
        m = int(member_counts[g % len(member_counts)])
        group = f"slice-{g:03d}"
        for i in range(m):
            pods.append(Pod(
                name=f"{group}-w{i:03d}",
                scheduler_name=scheduler_name,
                requests={"cpu": cpu, "mem": mem, "net_bw": netbw},
                pod_group=group,
                gang_min_member=m,
                priority=5.0,
            ))
    for i in range(filler_pods):
        pods.append(Pod(
            name=f"filler-{i:05d}",
            scheduler_name=scheduler_name,
            requests={
                "cpu": float(rng.uniform(0.1, 1.0)),
                "mem": float(rng.uniform(0.2, 2.0)),
                "net_bw": float(rng.uniform(0.02, 0.5)),
            },
            priority=float(rng.uniform(0, 10)),
        ))
    order = rng.permutation(len(pods))
    return [pods[int(j)] for j in order]


def generate_workload(spec: WorkloadSpec,
                      scheduler_name: str = "netAwareScheduler"
                      ) -> list[Pod]:
    """Pods grouped into services; pods of a service exchange traffic
    with earlier pods of the same service (so peers resolve as the
    batch schedules — the batch-internal dependency the conflict
    resolver must handle)."""
    rng = np.random.default_rng(spec.seed)
    pods: list[Pod] = []
    service_of = rng.integers(0, spec.services, spec.num_pods)
    by_service: dict[int, list[str]] = {}
    # Spread constraints are per-SERVICE (a Deployment template carries
    # them uniformly), decided on first sight of each service.
    svc_spread: dict[str, tuple[int, bool]] = {}
    for i in range(spec.num_pods):
        svc = int(service_of[i])
        name = f"pod-{svc:03d}-{i:05d}"
        earlier = by_service.setdefault(svc, [])
        peers: dict[str, float] = {}
        if earlier and rng.random() < spec.peer_fraction:
            count = int(rng.integers(1, spec.max_peers + 1))
            chosen = rng.choice(len(earlier), size=min(count, len(earlier)),
                                replace=False)
            for c in chosen:
                peers[earlier[int(c)]] = float(rng.uniform(0.5, 20.0))
        group = f"svc-{svc % 28}"  # bounded distinct groups (32-bit intern)
        affinity = (frozenset({group})
                    if rng.random() < spec.affinity_fraction else frozenset())
        anti = (frozenset({f"svc-{int(rng.integers(0, 28))}"})
                if rng.random() < spec.anti_fraction else frozenset())
        soft_node = ()
        if rng.random() < spec.soft_zone_fraction:
            zone = int(rng.integers(0, spec.zones))
            soft_node = ((frozenset({f"zone={zone}"}),
                          float(rng.uniform(40.0, 100.0))),)
        soft_group = ()
        if rng.random() < spec.soft_spread_fraction:
            soft_group = ((group, -float(rng.uniform(40.0, 100.0))),)
        if group not in svc_spread:
            if rng.random() < spec.spread_fraction:
                svc_spread[group] = (
                    int(rng.integers(1, 3)),
                    bool(rng.random() < spec.spread_hard_fraction))
            else:
                svc_spread[group] = (0, True)
        spread_skew, spread_hard = svc_spread[group]
        zone_aff = frozenset()
        if earlier and rng.random() < spec.zone_aff_fraction:
            # Followers only (an established service has zone members
            # to join); a first pod with self-affinity would deadlock.
            zone_aff = frozenset({group})
        zone_anti = frozenset()
        if rng.random() < spec.zone_anti_fraction:
            other = int(rng.integers(0, 28))
            if f"svc-{other}" != group:
                zone_anti = frozenset({f"svc-{other}"})
        req_ns = ()
        if rng.random() < spec.ns_fraction:
            req_ns = (((("In", "disk", ("ssd",)),)
                       if rng.random() < 0.5
                       else (("NotIn", "disk", ("hdd",)),)),)
        pods.append(Pod(
            name=name,
            scheduler_name=scheduler_name,
            requests={
                "cpu": float(rng.uniform(*spec.cpu_range)),
                "mem": float(rng.uniform(*spec.mem_range)),
                "net_bw": float(rng.uniform(*spec.netbw_range)),
            },
            peers=peers,
            tolerations=(frozenset({"dedicated"})
                         if rng.random() < spec.tolerate_fraction
                         else frozenset()),
            group=group,
            affinity_groups=affinity,
            anti_groups=anti,
            zone_affinity_groups=zone_aff,
            zone_anti_groups=zone_anti,
            required_node_affinity=req_ns,
            soft_node_affinity=soft_node,
            soft_group_affinity=soft_group,
            spread_maxskew=spread_skew,
            spread_hard=spread_hard,
            priority=float(rng.uniform(0, 10)),
        ))
        earlier.append(name)
    return pods
