"""Headline benchmark: clusterloader2-style density replay throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline semantics: the reference scheduler's cycle performs 5 serial
node_exporter scrapes plus 4 iperf-file reads per pod
(scheduler/scheduler.go:191, :275-279, :503-530) before picking a node.
On its 192.168.1.x LAN that bounds effective throughput at ~10 pods/sec
(>=10 ms per scrape round-trip, 5 in series, plus parsing ~100 KB
bodies ~25 times) — a deliberately generous ceiling used as
``vs_baseline`` denominator.  The north-star target is 10k pods/sec at
5k nodes (BASELINE.json).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


REFERENCE_PODS_PER_SEC = 10.0


def _tpu_reachable(timeout_s: float = 120.0) -> bool:
    """Probe device init in a THROWAWAY subprocess: when the axon
    tunnel is wedged, any in-process ``jax.devices()`` hangs forever
    at PJRT init (no exception to catch) — the probe must be
    killable."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            capture_output=True, timeout=timeout_s)
        return b"ok" in proc.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def _tpu_reachable_with_retries() -> bool:
    """The tunnel wedge ate the round-1 bench twice (builder AND
    judge re-run both fell back to CPU).  Retry the probe with backoff
    — a wedged tunnel sometimes recovers within minutes — before
    conceding to the CPU fallback.  BENCH_TPU_RETRIES=0 keeps the old
    single-shot behavior."""
    import time

    retries = int(os.environ.get("BENCH_TPU_RETRIES", "4"))
    backoff_s = float(os.environ.get("BENCH_TPU_BACKOFF_S", "90"))
    for attempt in range(retries + 1):
        if _tpu_reachable():
            return True
        if attempt < retries:
            print(f"TPU probe attempt {attempt + 1} failed; retrying "
                  f"in {backoff_s:.0f}s", file=sys.stderr)
            time.sleep(backoff_s)
    return False


_TPU_ART_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "bench_artifacts", "tpu")


def _persisted_tpu_density() -> dict | None:
    """A mid-round hardware run captured by tools/tpu_watch.py.

    The watcher probes the tunnel all round and, in any recovery
    window, runs the bench legs cheapest-first and persists each
    result (VERDICT r3 next-round #1).  If the tunnel is wedged again
    at driver time, that persisted headline — a REAL hardware
    measurement, schema-identical to this script's output — beats a
    CPU stand-in.  Provenance fields mark it as a replayed artifact.

    Guards (a stale or mismatched artifact must not masquerade as the
    current measurement): the artifact must target the same metric
    (same BENCH_NODES) and be younger than BENCH_TPU_ART_MAX_AGE_S
    (default 24 h — one round).  The recorded git SHA is surfaced in
    the provenance so a reviewer can diff artifact-code vs HEAD."""
    loaded = _load_green_leg("density_full")
    if loaded is None:
        return None
    leg, age_s = loaded
    max_age = float(os.environ.get("BENCH_TPU_ART_MAX_AGE_S", "86400"))
    if age_s > max_age:
        return None
    doc = leg.get("detail")  # tpu_legs.density_full stores bench.py's doc
    if not isinstance(doc, dict) or "metric" not in doc:
        return None
    want_nodes = os.environ.get("BENCH_NODES", "5120")
    if doc["metric"] != f"density_pods_per_sec_n{want_nodes}":
        return None
    doc.setdefault("detail", {})
    doc["detail"]["persisted"] = True
    doc["detail"]["measured_at"] = leg.get("ts", "")
    doc["detail"]["measured_git"] = leg.get("git", "")
    doc["detail"]["artifact_age_s"] = round(age_s)
    if "score_p99_source" not in doc["detail"]:
        # Artifact captured by a pre-round-5 bench.py: its score_* are
        # HOST-observed (tunnel transport included).  Re-label them
        # honestly and promote the watcher's device-boundary latency
        # artifact — a real hardware measurement at the same shape —
        # to the primary fields, with provenance.
        d = doc["detail"]
        d["host_score_p50_ms"] = d.get("score_p50_ms")
        d["host_score_p99_ms"] = d.get("score_p99_ms")
        d["host_score_samples"] = d.get("score_samples")
        dl = _persisted_device_latency(d.get("score_backend", "pallas"))
        if dl is not None:
            d["score_p50_ms"] = dl["p50_ms"]
            d["score_p99_ms"] = dl["p99_ms"]
            d["score_samples"] = dl["reps"]
            # Carry the leg's OWN methodology label (scan-amortized
            # captures say so; pre-r6 captures stay distinguishable).
            d["score_p99_source"] = (
                dl.get("p99_source", "device_boundary") + "_artifact")
            if dl.get("scan_k"):
                d["score_scan_k"] = dl["scan_k"]
            d["score_p99_artifact_git"] = dl.get("git", "")
        else:
            d["score_p99_source"] = "host_observed"
    elif (doc["detail"].get("score_p99_source") == "device_boundary"
          and "score_p99_methodology" not in doc["detail"]):
        # r5-era artifact: labeled device_boundary, but that round's
        # measure_device_latency passed HOST-numpy inputs into the
        # jitted step, re-uploading the N-node snapshot every rep —
        # its p99 is dominated by transfer, not the kernel (the
        # BENCH_r05 87.44 ms vs device_latency.json 3.4 ms
        # contradiction).  Re-label so the number can't be read as a
        # device-boundary latency; swap in the watcher's clean
        # device-latency artifact when one exists.
        d = doc["detail"]
        d["score_p99_source"] = "device_boundary_host_inputs"
        dl = _persisted_device_latency(d.get("score_backend", "pallas"))
        if dl is not None:
            d["host_upload_score_p99_ms"] = d.get("score_p99_ms")
            d["score_p50_ms"] = dl["p50_ms"]
            d["score_p99_ms"] = dl["p99_ms"]
            d["score_samples"] = dl["reps"]
            d["score_p99_source"] = (
                dl.get("p99_source", "device_boundary") + "_artifact")
            if dl.get("scan_k"):
                d["score_scan_k"] = dl["scan_k"]
            d["score_p99_artifact_git"] = dl.get("git", "")
    return doc


def _load_green_leg(name: str) -> tuple[dict, float] | None:
    """A watcher-captured leg artifact that reported ok=True, with
    its age in seconds; None when absent, unparseable, or failed."""
    path = os.path.join(_TPU_ART_DIR, f"{name}.json")
    try:
        with open(path) as f:
            leg = json.load(f)
        age_s = time.time() - os.path.getmtime(path)
    except (OSError, ValueError):
        return None
    if not leg.get("ok"):
        return None
    return leg, age_s


def _persisted_device_latency(backend: str) -> dict | None:
    """The watcher's ``device_latency`` leg for one score backend
    (tools/tpu_legs.leg_device_latency), with the capturing git SHA
    attached; None when absent/failed."""
    loaded = _load_green_leg("device_latency")
    if loaded is None:
        return None
    leg, _age = loaded
    sub = leg.get("detail", {}).get(backend)
    if not isinstance(sub, dict) or "p99_ms" not in sub:
        return None
    sub = dict(sub)
    sub["git"] = leg.get("git", "")
    return sub


def _persisted_integrity() -> dict | None:
    """The ``--suite integrity`` leg's artifact
    (bench_artifacts/integrity.json), compressed to the block r10+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 10): audit enabled, measured overhead
    fraction, and zero unrepaired drift across the fault matrix.
    None when the leg has not run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "integrity.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        d = doc["detail"]
        return {
            "audit_enabled": bool(d["audit_enabled"]),
            "overhead_fraction": float(d["overhead_fraction"]),
            "audit_per_cycle_fraction": float(
                d.get("audit_per_cycle_fraction", 0.0)),
            "audit_ms_p50": float(d.get("audit_ms_p50", 0.0)),
            "audits": int(d.get("audits", 0)),
            "clean_run_bit_identical": bool(
                d.get("clean_run_bit_identical", False)),
            "all_faults_detected": bool(
                d.get("all_faults_detected", False)),
            "unrepaired_drift": int(d.get("unrepaired_drift", 0)),
            "source": "suite_integrity",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_quality() -> dict | None:
    """The ``--suite quality`` leg's artifact
    (bench_artifacts/quality.json), compressed to the block r11+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 11): observation enabled, measured
    serving overhead with the quality observer riding every commit,
    and a live calibration sample count.  None when the leg has not
    run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "quality.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        d = doc["detail"]
        return {
            "observation_enabled": bool(d["observation_enabled"]),
            "overhead_fraction": float(d["overhead_fraction"]),
            "calibration_samples": int(d["calibration_samples"]),
            "bit_identical": bool(d.get("bit_identical", False)),
            "regret_p99": float(d.get("regret_p99", 0.0)),
            "harvest_ms_p50": float(d.get("harvest_ms_p50", 0.0)),
            "source": "suite_quality",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_rebalance() -> dict | None:
    """The ``--suite rebalance`` leg's artifact
    (bench_artifacts/rebalance.json), compressed to the block r12+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 12): rebalancer enabled, zero half-moved
    gangs, and disruption (evictions/pod/hour) beside the configured
    budget.  None when the leg has not run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "rebalance.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        d = doc["detail"]
        return {
            "enabled": bool(d["rebalance_enabled"]),
            "half_moved_gangs": int(d["half_moved_gangs"]),
            "evictions_per_pod_hour": float(
                d["evictions_per_pod_hour"]),
            "budget_per_pod_hour": float(d["budget_per_pod_hour"]),
            "recovered_frac": float(d.get("recovered_frac", 0.0)),
            "no_drift_moves": int(d.get("no_drift_moves", 0)),
            "moves": int(d.get("moves", 0)),
            "source": "suite_rebalance",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_reshape() -> dict | None:
    """The ``--suite reshape`` leg's artifact
    (bench_artifacts/reshape.json), compressed to the block r17+
    artifacts must carry when claiming gang or rebalance results
    (tools/bench_check Rule 17): reshaping enabled, ZERO half-shaped
    gangs, and reshape disruption beside the configured eviction
    budget.  None when the leg has not run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "reshape.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        d = doc["detail"]["reshape"]
        return {
            "enabled": bool(d["enabled"]),
            "half_shaped_gangs": int(d["half_shaped_gangs"]),
            "evictions_per_pod_hour": float(
                d["evictions_per_pod_hour"]),
            "budget_per_pod_hour": float(d["budget_per_pod_hour"]),
            "recovered_frac": float(d.get("recovered_frac", 0.0)),
            "reshapes_total": int(d.get("reshapes_total", 0)),
            "no_outage_reshapes": int(d.get("no_outage_reshapes", 0)),
            "source": "suite_reshape",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_scenario() -> dict | None:
    """The ``--suite scenario`` leg's artifact
    (bench_artifacts/scenario.json), compressed to the block r13+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 13): how many pods streamed through the
    live loop, the full outcome scorecard, zero half-moved gangs, and
    the peak-RSS bound.  None when the leg has not run in this
    tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "scenario.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        d = doc["detail"]
        return {
            "pods_streamed": int(d["pods_streamed"]),
            "scorecard": dict(d["scorecard"]),
            "half_moved_gangs": int(d["half_moved_gangs"]),
            "peak_rss_bytes": int(d.get("peak_rss_bytes", 0)),
            "pods_per_wall_second": float(doc.get("value", 0.0)),
            "source": "suite_scenario",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_policy() -> dict | None:
    """The ``--suite policy`` leg's artifact
    (bench_artifacts/policy.json), compressed to the block r14+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 14): measured shadow-scoring overhead,
    proof the disabled path stayed bit-identical, and the promotion
    gate's provenance (a seeded loser refused, a seeded winner
    promoted with the counterfactual-replay deltas on its face).
    None when the leg has not run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "policy.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        p = doc["detail"]["policy"]
        return {
            "shadow_overhead_fraction": float(
                p["shadow_overhead_fraction"]),
            "disabled_bit_identical": bool(
                p["disabled_bit_identical"]),
            "gate_rejects_loser": bool(p["gate_rejects_loser"]),
            "promoted": bool(p.get("promoted", False)),
            "promotion": dict(p.get("promotion", {})),
            "oracle_gain_recovered_fraction": float(
                p.get("oracle_gain_recovered_fraction", 0.0)),
            "shadow_disagreement_rate": float(
                p.get("shadow_disagreement_rate", 0.0)),
            "source": "suite_policy",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _persisted_fleet() -> dict | None:
    """The ``--suite fleet`` leg's artifact
    (bench_artifacts/fleet.json), compressed to the block r15+
    density artifacts must carry when claiming the p99 bar
    (tools/bench_check Rule 15): the per-tenant isolation proof
    (every tenant's placements bit-identical to solo serving), the
    per-tenant SLO blocks, and the consolidation numbers.  None when
    the leg has not run in this tree."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_artifacts", "fleet.json")
    try:
        with open(path) as f:
            doc = json.load(f)
        flt = doc["detail"]["fleet"]
        return {
            "isolation_bit_identical": bool(
                flt["isolation_bit_identical"]),
            "tenants": {
                name: {"slo": dict(t.get("slo", {})),
                       "score_p99_ms": float(
                           t.get("score_p99_ms", 0.0)),
                       "bit_identical_to_solo": bool(
                           t.get("bit_identical_to_solo", False))}
                for name, t in flt["tenants"].items()},
            "aggregate_pods_per_sec": float(
                flt["aggregate_pods_per_sec"]),
            "single_tenant_pods_per_sec": float(
                flt["single_tenant_pods_per_sec"]),
            "speedup": float(flt["speedup"]),
            "transfer": {
                "examples_to_promotion_cold": flt.get(
                    "transfer", {}).get("examples_to_promotion_cold"),
                "examples_to_promotion_warm": flt.get(
                    "transfer", {}).get("examples_to_promotion_warm"),
                "warm_lt_cold": bool(flt.get("transfer", {}).get(
                    "warm_lt_cold", False)),
            },
            "source": "suite_fleet",
        }
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _mark_driver_active():
    """Touch driver.intent and take chip.lock so the round-long
    watcher yields the single-owner chip to this run (it re-checks the
    flag between legs).  Best-effort: lock acquisition waits at most
    BENCH_LOCK_WAIT_S for a watcher leg to finish, then proceeds — the
    startup probe decides what actually happens."""
    try:
        os.makedirs(_TPU_ART_DIR, exist_ok=True)
        with open(os.path.join(_TPU_ART_DIR, "driver.intent"), "w") as f:
            f.write(str(os.getpid()))
    except OSError:
        return None
    try:
        import fcntl
        import time

        lock_f = open(os.path.join(_TPU_ART_DIR, "chip.lock"), "w")
        deadline = time.time() + float(
            os.environ.get("BENCH_LOCK_WAIT_S", "900"))
        while time.time() < deadline:
            try:
                fcntl.flock(lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return lock_f
            except OSError:
                time.sleep(5)
        print("WARNING: chip.lock still held after wait; proceeding",
              file=sys.stderr)
        return lock_f
    except Exception:  # noqa: BLE001
        return None


def _clear_driver_intent() -> None:
    try:
        os.remove(os.path.join(_TPU_ART_DIR, "driver.intent"))
    except OSError:
        pass


def _probe_log_stats() -> dict:
    """Proof-of-probing for the round: how many tunnel probes the
    watcher made and whether any succeeded (VERDICT r3 done-criterion:
    'a log proving N probe attempts spread across the whole round')."""
    path = os.path.join(_TPU_ART_DIR, "probe_log.jsonl")
    total = ok = 0
    first = last = ""
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("note"):
                    continue  # watcher start markers
                total += 1
                ok += 1 if rec.get("ok") else 0
                last = rec.get("ts", "")
                first = first or last
    except OSError:
        return {}
    return {"probe_attempts": total, "probe_successes": ok,
            "probe_first": first, "probe_last": last}


def _run_backend_subprocess(backend: str, force_cpu: bool,
                            timeout_s: float | None = None,
                            env_extra: dict | None = None) -> dict:
    """Re-invoke this script pinned to one score backend and parse its
    headline JSON doc back.

    In the backend-comparison mode EVERY leg runs this way and the
    parent never initializes a JAX backend at all: the TPU is a
    single-owner device, so an in-process parent leg would hold the
    chip and make the second leg's PJRT init fail or hang for the
    whole timeout."""
    timeout_s = timeout_s if timeout_s is not None else float(
        os.environ.get("BENCH_BACKEND_TIMEOUT_S", "900"))
    env = dict(os.environ)
    env["BENCH_SCORE_BACKEND"] = backend
    env["BENCH_SKIP_TPU_PROBE"] = "1"  # parent already probed
    env["BENCH_CHILD"] = "1"  # suppresses the child's own CPU fallback
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run([sys.executable, __file__],
                          capture_output=True, timeout=timeout_s,
                          env=env)
    if proc.returncode != 0:
        raise RuntimeError(
            f"subprocess rc={proc.returncode}: "
            f"{proc.stderr.decode(errors='replace')[-300:]}")
    line = proc.stdout.decode().strip().splitlines()[-1]
    return json.loads(line)


def _measure_device_leg(num_nodes: int, batch: int,
                        backend: str) -> dict | None:
    """Scan-amortized device schedule-step latency at the bench shape
    (VERDICT r4 #2: the artifact's PRIMARY p99 must be measured where
    the north-star bar means it — at the device, not through the
    tunnel's fetch RTT).  Since round 6 each sample is ``scan_k``
    chained steps inside one jitted ``lax.scan`` divided by
    ``scan_k``, so per-dispatch transport cannot masquerade as kernel
    latency (docs/ROUND_NOTES.md, the 87-vs-3.4 ms root cause).  None
    on failure or ``BENCH_DEVICE_REPS=0``; the caller falls back to
    host-observed numbers, labeled as such."""
    try:
        import jax

        from kubernetesnetawarescheduler_tpu.bench.density import (
            measure_device_latency,
        )

        # Default reps gated on the EXECUTED backend: scan-amortized
        # samples each cost scan_k chained N=5120 steps — cheap on the
        # chip, meaningful extra scoring work on the CPU leg.
        default = "50" if jax.default_backend() == "tpu" else "20"
        reps = int(os.environ.get("BENCH_DEVICE_REPS", default))
        if reps <= 0:
            return None  # canary runs opt out of the microbench
        return measure_device_latency(num_nodes, batch,
                                      score_backend=backend, reps=reps)
    except Exception as exc:  # noqa: BLE001 — the density headline
        # must survive a microbench failure
        print(f"WARNING: device-latency leg failed: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return None


def _measure_multicycle_leg(num_nodes: int, batch: int,
                            backend: str) -> dict | None:
    """Device-BOUNDARY per-cycle latency of the persistent K-cycle
    window (ISSUE 17): one replay dispatch over K device-resident
    waves + ONE assignments fetch, wall / K — the cost a retire
    actually pays, amortized by the window instead of paid per cycle
    (r5's 87 ms gap).  K from BENCH_MULTICYCLE (default 8; <=1 skips
    the leg).  None on failure; detail.multicycle then carries no
    boundary block and Rule 16 withholds the p99 claim."""
    try:
        k = int(os.environ.get("BENCH_MULTICYCLE", "8"))
        if k <= 1:
            return None
        from kubernetesnetawarescheduler_tpu.bench.density import (
            measure_multicycle_latency,
        )

        reps = int(os.environ.get("BENCH_MULTICYCLE_REPS", "20"))
        if reps <= 0:
            return None
        return measure_multicycle_latency(num_nodes, batch, k=k,
                                          score_backend=backend,
                                          reps=reps)
    except Exception as exc:  # noqa: BLE001 — same survival contract
        # as the device-latency leg
        print(f"WARNING: multicycle-latency leg failed: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return None


def _multicycle_identity_leg() -> dict | None:
    """Placement bit-identity A/B (ISSUE 17 acceptance): a seeded
    drain at multicycle K + coalesced binds vs the SAME drain at K=1
    with coalescing off (exactly the r15 per-cycle path).  Small
    shape on purpose — identity is structural, not scale-dependent —
    and CPU-cheap enough to ride every run."""
    try:
        k = int(os.environ.get("BENCH_MULTICYCLE", "8"))
        if k <= 1:
            return None
        from kubernetesnetawarescheduler_tpu.bench.density import (
            multicycle_identity_check,
        )

        return multicycle_identity_check(
            num_nodes=128, batch_size=16, k=k,
            coalesce=int(os.environ.get("BENCH_BIND_COALESCE", "4")),
            inflight=int(os.environ.get("BENCH_BIND_INFLIGHT", "2")),
            num_pods=192)
    except Exception as exc:  # noqa: BLE001
        print(f"WARNING: multicycle identity leg failed: "
              f"{type(exc).__name__}: {exc}", file=sys.stderr)
        return None


def _assemble_doc(res, *, num_nodes: int, batch: int, method: str,
                  mode: str, executed_backend: str, score_backend: str,
                  mesh_desc: str, device_lat: dict | None,
                  multicycle_lat: dict | None = None,
                  multicycle_ab: dict | None = None) -> dict:
    """The headline JSON doc for one fully-executed density leg.

    ``score_p50/p99_ms`` are the SCAN-AMORTIZED device percentiles of
    the per-batch schedule step (assign + commit on the serving
    loop's cached static) when the microbench succeeded
    (``score_p99_source: "device_scan_amortized"``): each sample is
    ``scan_k`` chained steps in ONE jitted ``lax.scan`` dispatch
    divided by ``scan_k``, so per-dispatch transport amortizes to
    1/scan_k and cannot masquerade as kernel latency.  This is the
    single primary methodology — tools/tpu_legs.leg_device_latency
    measures the same way, so the two must agree within noise.  The
    drain's host-observed numbers are always preserved under
    ``host_score_*``: in pipeline mode those are per-batch
    steady-state SERVICE times (chunk arrival gaps with the dispatch
    window full — they can legitimately sit below the isolated
    dispatch latency), and on a tunneled chip they additionally carry
    the ~65 ms fetch RTT.  ``host_score_samples`` counts per-batch
    WEIGHTED observations since round 5's PhaseTimer change."""
    detail = {
        "pods_bound": res.pods_bound,
        "pods_unschedulable": res.pods_unschedulable,
        "host_score_p50_ms": round(res.score_p50_ms, 2),
        "host_score_p99_ms": round(res.score_p99_ms, 2),
        "host_score_samples": res.score_samples,
        "encode_p99_ms": round(res.encode_p99_ms, 2),
        "bind_p99_ms": round(res.bind_p99_ms, 2),
        "batch_size": batch,
        "method": method,
        "mode": mode,
        "backend": executed_backend,
        "score_backend": score_backend,
        "mesh": mesh_desc,
        # Conflict-round distribution of assign_parallel (one sample
        # per batch): whether device latency is matmul-bound or
        # round-bound (VERDICT.md round 2, weak #1).
        "rounds_p50": round(getattr(res, "rounds_p50", 0.0), 1),
        "rounds_p99": round(getattr(res, "rounds_p99", 0.0), 1),
        "rounds_max": int(getattr(res, "rounds_max", 0)),
    }
    tail = getattr(res, "bind_tail_ms", 0.0)
    if tail:
        # Residual bind drain after the last fetch — what r5's
        # pipeline mode wrongly published as bind_p99_ms (905.74 ms).
        # bind_p99_ms above is now a true per-batch percentile.
        detail["bind_tail_ms"] = round(tail, 2)
    budgets = getattr(res, "pipeline_budgets", None)
    if budgets:
        # Per-stage (encode / dispatch / device_wait / bind) budget
        # block from the serving loop's PhaseTimer: the artifact
        # carries the overlap structure on its face.
        detail["pipeline_budgets"] = budgets
    if hasattr(res, "static_refresh_count"):
        # Incremental device-resident state (r7): how the static was
        # kept fresh during the measured window — refresh count +
        # latency (off the serving critical path when async), the
        # staleness of the static each Score() actually used vs its
        # configured bound, and delta-vs-full snapshot upload bytes.
        # count==1 with delta_bytes==0 means a churn-free run (the
        # initial build only) — honest, not missing instrumentation.
        detail["static_refresh"] = {
            "count": int(res.static_refresh_count),
            "p99_ms": round(res.static_refresh_p99_ms, 3),
            "sync_builds": int(getattr(res, "static_sync_builds", 0)),
            "staleness_at_score_p50_ms": round(
                getattr(res, "staleness_at_score_p50_ms", 0.0), 3),
            "staleness_at_score_p99_ms": round(
                getattr(res, "staleness_at_score_p99_ms", 0.0), 3),
            "staleness_bound_s": float(
                getattr(res, "staleness_bound_s", 0.0)),
            "delta_bytes": int(getattr(res, "delta_bytes", 0)),
            "full_bytes": int(getattr(res, "full_bytes", 0)),
        }
    if hasattr(res, "bind_queue_wait_p99_ms"):
        # Bind-tail split (r7): r5's 905.74 ms "bind_p99_ms" was drain
        # serialization; this block says where bind time actually goes
        # — queue wait (assignment fetched, binder busy), the
        # un-normalized _bind_all round-trip, and transient retries.
        detail["bind_split"] = {
            "queue_wait_p99_ms": round(res.bind_queue_wait_p99_ms, 3),
            "rtt_p99_ms": round(getattr(res, "bind_rtt_p99_ms", 0.0),
                                3),
            "retry_count": int(getattr(res, "bind_retry_count", 0)),
            # Coalesced async binds (r16, bench_check Rule 16): the
            # inflight bound the drain ran under, its measured
            # high-water mark, and how many queued batches were folded
            # into an adjacent batch's fanout.
            "max_inflight": int(
                getattr(res, "bind_max_inflight", 1) or 1),
            "coalesce_window": int(
                getattr(res, "bind_coalesce_window", 1) or 1),
            "coalesced_total": int(
                getattr(res, "bind_coalesced_total", 0)),
            "inflight_peak": int(
                getattr(res, "bind_inflight_peak", 0)),
        }
    if getattr(res, "trace_provenance", None):
        # Decision-level trace provenance (r8, bench_check Rule 8):
        # ring-buffer accounting + the worst retained cycle span, so
        # any claimed p99 is attributable to a concrete cycle.  The
        # full Perfetto-loadable trace lands at trace_out when
        # --trace-out / BENCH_TRACE_OUT is set.
        detail["trace_provenance"] = res.trace_provenance
    integ = _persisted_integrity()
    if integ is not None:
        # State-integrity provenance (r10, bench_check Rule 10): the
        # p99 claim only counts if it was measured with the
        # anti-entropy auditor's overhead accounted for and the fault
        # matrix fully repaired (--suite integrity leg).
        detail["integrity"] = integ
    qual = _persisted_quality()
    if qual is not None:
        # Outcome-observability provenance (r11, bench_check Rule 11):
        # the p99 claim only counts if it was measured with the
        # quality observer's commit-seam cost accounted for and the
        # join actually producing calibration samples (--suite
        # quality leg).
        detail["quality"] = qual
    reb = _persisted_rebalance()
    if reb is not None:
        # Continuous-rebalancing provenance (r12, bench_check Rule
        # 12): the p99 claim only counts alongside proof that the
        # descheduler kept disruption inside its eviction budget and
        # never stranded a half-moved gang (--suite rebalance leg).
        detail["rebalance"] = reb
    resh = _persisted_reshape()
    if resh is not None:
        # Elastic-reshaping provenance (r17, bench_check Rule 17):
        # any artifact claiming gang or rebalance results must also
        # prove the degrade-and-recover path never stranded a
        # half-shaped gang and stayed inside the eviction budget
        # (--suite reshape leg).
        detail["reshape"] = resh
    scen = _persisted_scenario()
    if scen is not None:
        # Scenario-campaign provenance (r13, bench_check Rule 13):
        # the p99 claim only counts alongside proof that the whole
        # stack streamed a trace-driven campaign with the scorecard
        # published and gang atomicity intact (--suite scenario leg).
        detail["scenario"] = scen
    pol = _persisted_policy()
    if pol is not None:
        # Learned-scoring provenance (r14, bench_check Rule 14): the
        # p99 claim only counts alongside proof that shadow scoring
        # stayed under its overhead bar, the disabled path stayed
        # bit-identical, and every promotion traces to a
        # counterfactual-replay win (--suite policy leg).
        detail["policy"] = pol
    flt = _persisted_fleet()
    if flt is not None:
        # Fleet-consolidation provenance (r15, bench_check Rule 15):
        # the p99 claim only counts alongside proof that batching
        # many tenants' planes into one device state kept every
        # tenant's placements bit-identical to solo serving and each
        # tenant's SLO block published (--suite fleet leg).
        detail["fleet"] = flt
    if device_lat is not None:
        detail.update({
            "score_p50_ms": device_lat["p50_ms"],
            "score_p99_ms": device_lat["p99_ms"],
            "score_max_ms": device_lat["max_ms"],
            "score_samples": device_lat["reps"],
            "score_scan_k": device_lat.get("scan_k"),
            "score_static_prep_ms": device_lat.get("static_prep_ms"),
            "score_p99_source": device_lat.get(
                "p99_source", "device_scan_amortized"),
            # Methodology marker: scan_k chained steps in one jitted
            # lax.scan, wall / scan_k per sample, inputs device_put
            # ONCE (bench/density.measure_device_latency).  Absent in
            # r5-era artifacts, whose "device_boundary" numbers
            # re-uploaded the host snapshot every rep and read
            # transfer time as kernel latency (87 ms vs the true
            # 3.4 ms at N=5120 through the dev tunnel — root cause in
            # docs/ROUND_NOTES.md round 6).
            "score_p99_methodology": "lax_scan_chained_steps",
            # What the host sees beyond the device's own latency:
            # dispatch/fetch transport (the dev tunnel's RTT when
            # present; near zero co-located).
            "host_transport_p50_ms": round(max(
                0.0, res.score_p50_ms - device_lat["p50_ms"]), 2),
        })
        if device_lat.get("winner_fusion") is not None:
            # Fused-winner provenance (r9, bench_check Rule 9): the
            # per-dispatch fused-vs-unfused A/B, donation accounting
            # (verified buffer-deleted, not assumed), and the fused
            # leg's conflict-round histogram — any r9+ artifact
            # claiming the p99 bar must carry this block.
            detail["winner_fusion"] = device_lat["winner_fusion"]
    else:
        detail.update({
            "score_p50_ms": round(res.score_p50_ms, 2),
            "score_p99_ms": round(res.score_p99_ms, 2),
            "score_samples": res.score_samples,
            "score_p99_source": "host_observed",
        })
    if multicycle_lat is not None or getattr(res, "multicycle_k",
                                             0) > 1:
        # Persistent multi-cycle provenance (r16, bench_check Rule
        # 16): any r16+ artifact claiming the p99 bar must say which
        # K it amortized over, how deep the device wave queue was,
        # and how late waves retired — plus the boundary-vs-kernel
        # ratio the window exists to close (ISSUE 17: boundary p99
        # within 2x of the scan-amortized in-kernel p99).
        mc: dict = {
            "k": int(getattr(res, "multicycle_k", 0)
                     or (multicycle_lat or {}).get("multicycle_k", 0)),
            "device_queue_depth": int(
                getattr(res, "multicycle_queue_depth", 0)),
            "windows": int(getattr(res, "multicycle_windows", 0)),
            "overflow": int(getattr(res, "multicycle_overflow", 0)),
            "retire_lag_p99": float(
                getattr(res, "retire_lag_p99", 0.0)),
        }
        if multicycle_lat is not None:
            mc["device_boundary"] = multicycle_lat
            if mc["k"] <= 1:
                mc["k"] = int(multicycle_lat.get("multicycle_k", 0))
            if not mc["device_queue_depth"]:
                # Microbench stages the whole window device-resident
                # — the ring depth it models equals K.
                mc["device_queue_depth"] = int(
                    multicycle_lat.get("multicycle_k", 0))
            if device_lat is not None and device_lat.get("p99_ms"):
                ratio = (multicycle_lat["p99_ms"]
                         / device_lat["p99_ms"])
                mc["boundary_over_scan_ratio"] = round(ratio, 2)
                mc["within_2x_scan"] = ratio <= 2.0
        if multicycle_ab is not None:
            mc["identity_ab"] = multicycle_ab
        detail["multicycle"] = mc
    return {
        "metric": f"density_pods_per_sec_n{num_nodes}",
        "value": round(res.pods_per_sec, 1),
        "unit": "pods/s",
        "vs_baseline": round(res.pods_per_sec / REFERENCE_PODS_PER_SEC,
                             2),
        "detail": detail,
    }


def _attach_bench_env(doc: dict) -> None:
    """Machine/tree provenance on every emitted doc (host, cores,
    1-min loadavg, git sha) — the block every artifact carries so a
    number traces to where it was produced."""
    try:
        from kubernetesnetawarescheduler_tpu.bench.envinfo import (
            bench_env,
        )

        doc.setdefault("detail", {})["bench_env"] = bench_env()
    except Exception:  # noqa: BLE001 — provenance must not fail a run
        pass


def _attach_north_star(doc: dict) -> None:
    """Self-certify the BASELINE.json bar inside the artifact
    (VERDICT r4 #2: the driver artifact must pass/fail the p99 bar on
    its face, no cross-referencing).  ``p99_met`` is judged on the
    primary (device-boundary when available) p99."""
    detail = doc["detail"]
    p99 = float(detail.get("score_p99_ms", 1e9))
    ns = {
        "pods_per_sec_target": 10000.0,
        "p99_bar_ms": 5.0,
        "pods_per_sec_met": float(doc["value"]) >= 10000.0,
        "p99_met": p99 < 5.0,
        "p99_source": detail.get("score_p99_source", "unknown"),
    }
    detail["north_star"] = ns
    if detail.get("backend") == "tpu" and not (
            ns["pods_per_sec_met"] and ns["p99_met"]):
        print(f"WARNING: north-star bar missed on TPU: {ns}",
              file=sys.stderr)


def _attach_cpu_density(doc: dict) -> None:
    """A CPU density canary rides along with every TPU (or
    persisted-TPU) headline so backend regressions on the always-
    available backend are caught even on tunnel-wedge rounds
    (VERDICT r4 #6).

    Round 6: FIXED-length runs (pod count no longer derived from
    BENCH_PODS, so blocks are comparable across rounds) repeated
    ``BENCH_CPU_RUNS`` (>=3) times, with {mean, min, max, runs} in
    the block — a single run cannot distinguish a real regression
    from load noise on a shared host.  ``regression_flagged`` trips
    when the within-block spread exceeds 15% of the mean; reviewers
    comparing means across rounds should apply the same 15% bar."""
    if os.environ.get("BENCH_SKIP_CPU_LEG", "") == "1":
        return
    cpu_pods = os.environ.get("BENCH_CPU_PODS", "16384")
    n_runs = max(1, int(os.environ.get("BENCH_CPU_RUNS", "3")))
    timeout_s = float(os.environ.get("BENCH_CPU_TIMEOUT_S", "3600"))
    values: list[float] = []
    first_detail: dict = {}
    try:
        for i in range(n_runs):
            sub = _run_backend_subprocess(
                "xla", force_cpu=True, timeout_s=timeout_s,
                env_extra={"BENCH_PODS": cpu_pods,
                           # Only the first run carries the device-
                           # latency microbench; the repeats are pure
                           # throughput samples.
                           "BENCH_DEVICE_REPS":
                               "20" if i == 0 else "0",
                           "BENCH_MESH": "off"})
            values.append(float(sub["value"]))
            if i == 0:
                first_detail = sub["detail"]
        mean = sum(values) / len(values)
        spread_pct = ((max(values) - min(values)) / mean * 100.0
                      if mean else 0.0)
        d = first_detail
        doc["detail"]["cpu_density"] = {
            "pods_per_sec": {
                "mean": round(mean, 1),
                "min": round(min(values), 1),
                "max": round(max(values), 1),
                "runs": len(values),
            },
            "num_pods": int(cpu_pods),
            "spread_pct": round(spread_pct, 1),
            "regression_flagged": spread_pct > 15.0,
            "score_p50_ms": d.get("score_p50_ms"),
            "score_p99_ms": d.get("score_p99_ms"),
            "score_p99_source": d.get("score_p99_source"),
            "host_score_p99_ms": d.get("host_score_p99_ms"),
            "mode": d.get("mode"),
            "measured_now": True,
        }
        if spread_pct > 15.0:
            print(f"WARNING: CPU density canary spread {spread_pct:.1f}% "
                  f"> 15% across {len(values)} runs: {values}",
                  file=sys.stderr)
    except Exception as exc:  # noqa: BLE001
        doc["detail"]["cpu_density_error"] = \
            f"{type(exc).__name__}: {exc}"
        if values:
            # Partial runs still carry signal; publish what completed.
            doc["detail"]["cpu_density_partial"] = \
                [round(v, 1) for v in values]
        print(f"WARNING: CPU density leg failed: {exc}",
              file=sys.stderr)


def _run_chaos_bench() -> None:
    """``bench.py --chaos``: control-plane brownout soak ->
    ``bench_artifacts/chaos.json``.

    No device work — the soak exercises the chaos proxy, circuit
    breaker, degraded mode and relist audit on virtual time — so it
    pins jax to CPU (like tools/soak.py) and never touches the TPU
    probe/ownership machinery.  The headline value is brownout
    throughput: pods assumed per cycle WHILE a fault window was
    active (degraded mode must keep scoring, not stall).  Exit 1
    when an invariant is violated or recovery never happened, so the
    driver fails loudly instead of committing a sick artifact."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from kubernetesnetawarescheduler_tpu.k8s.chaos import (
        run_chaos_soak,
    )

    doc = run_chaos_soak(
        seed=int(os.environ.get("BENCH_CHAOS_SEED", "0")),
        num_nodes=int(os.environ.get("BENCH_CHAOS_NODES", "32")),
        num_pods=int(os.environ.get("BENCH_CHAOS_PODS", "192")))
    doc["value"] = doc["detail"]["brownout"]["assumed_per_cycle"]
    doc["unit"] = "pods_assumed_per_cycle_during_brownout"
    _attach_bench_env(doc)
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "bench_artifacts", "chaos.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps(doc))
    bad = {k: v for k, v in doc["invariants"].items() if v}
    if bad or not doc.get("recovered"):
        print(f"WARNING: chaos soak unhealthy: invariants={bad} "
              f"recovered={doc.get('recovered')}", file=sys.stderr)
        sys.exit(1)


def _run_suite_bench(name: str) -> None:
    """``bench.py --suite <config>``: run one bench-suite leg into
    ``bench_artifacts/`` on CPU (the suite legs are replay harnesses,
    not device benchmarks — CPU keeps them runnable anywhere and the
    seeded artifacts reproducible).

    For the ``topology`` leg the ISSUE bars are checked here: blended
    gang placement must recover >= 80% of the oracle's bandwidth gain
    with probes covering < 5% of pairs — exit 1 otherwise so the
    driver fails loudly instead of committing a sick artifact."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from kubernetesnetawarescheduler_tpu.bench.suite import run_suite

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "bench_artifacts")
    small = os.environ.get("BENCH_SUITE_SMALL", "") == "1"
    (res,) = run_suite([name], out_dir=out, small=small)
    print(json.dumps(res.to_dict()))
    # Small shapes deliberately over-probe (coverage bar is a
    # full-shape property); only full runs are held to the bars.
    if name == "topology" and not small:
        detail = res.metrics.get("detail", {})
        if not (detail.get("gain_target_met")
                and detail.get("coverage_under_5pct")):
            print("WARNING: topology bars unmet: "
                  f"gain_ratio={detail.get('gain_ratio')} "
                  f"coverage={detail.get('coverage_fraction')}",
                  file=sys.stderr)
            sys.exit(1)
    if name == "integrity":
        detail = res.metrics.get("detail", {})
        # Every bar holds at every shape: the overhead fraction is the
        # audit's share of serving at the default background cadence,
        # which does not depend on smoke-run cycle sizes.
        bad = []
        if not detail.get("all_faults_detected"):
            bad.append("fault classes went undetected")
        if detail.get("unrepaired_drift", 1) != 0:
            bad.append(
                f"unrepaired_drift={detail.get('unrepaired_drift')}")
        if not detail.get("clean_run_bit_identical"):
            bad.append("clean-run placements changed under audit")
        if not detail.get("overhead_under_5pct"):
            bad.append("audit overhead "
                       f"{detail.get('overhead_fraction')} >= 5% "
                       "of serving at the default audit cadence")
        if bad:
            print("WARNING: integrity bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)
    if name == "quality":
        detail = res.metrics.get("detail", {})
        # Every bar holds at every shape: bit-identity and nonzero
        # calibration are structural; the overhead fraction is a p50
        # ratio, which smoke-run cycle sizes do not bias.
        bad = []
        if not detail.get("bit_identical"):
            bad.append("observation CHANGED placements")
        if not detail.get("overhead_under_2pct"):
            bad.append("observation overhead "
                       f"{detail.get('overhead_fraction')} >= 2% "
                       "of serving cycle p50")
        if detail.get("calibration_samples", 0) <= 0:
            bad.append("zero calibration samples (the join ran "
                       "blind)")
        if not detail.get("drift_detected"):
            bad.append("injected network drift did not move the "
                       "calibration residuals")
        if bad:
            print("WARNING: quality bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)
    if name == "rebalance":
        detail = res.metrics.get("detail", {})
        # Structural bars hold at every shape: hysteresis quiet on a
        # healthy cluster, disruption inside the budget, zero
        # half-moved gangs.  The recovery fraction is a full-shape
        # property (small shapes under-fragment), so only full runs
        # are held to >= 0.6.
        bad = []
        if detail.get("half_moved_gangs", 1) != 0:
            bad.append("half_moved_gangs="
                       f"{detail.get('half_moved_gangs')}")
        if detail.get("no_drift_moves", 1) != 0:
            bad.append("hysteresis failed to hold: "
                       f"{detail.get('no_drift_moves')} moves on a "
                       "healthy cluster")
        if not detail.get("no_drift_bit_identical"):
            bad.append("idle rebalancer CHANGED placements")
        if (detail.get("evictions_per_pod_hour", 1e9)
                > detail.get("budget_per_pod_hour", 0.0)):
            bad.append("disruption "
                       f"{detail.get('evictions_per_pod_hour')} over "
                       f"budget {detail.get('budget_per_pod_hour')}")
        if not small and detail.get("recovered_frac", 0.0) < 0.6:
            bad.append("recovered "
                       f"{detail.get('recovered_frac')} < 0.6 of "
                       "oracle bandwidth gain")
        if bad:
            print("WARNING: rebalance bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)
    if name == "reshape":
        detail = res.metrics.get("detail", {})
        resh = detail.get("reshape", {})
        # Structural bars hold at every shape: zero half-shaped
        # gangs, a silent reshape pass on a healthy cluster, and
        # disruption inside the eviction budget.  The recovery
        # fraction is a full-shape property (small shapes leave too
        # little room between the half and full realizations), so
        # only full runs are held to > 0.5.
        bad = []
        if resh.get("half_shaped_gangs", 1) != 0:
            bad.append("half_shaped_gangs="
                       f"{resh.get('half_shaped_gangs')}")
        if resh.get("no_outage_reshapes", 1) != 0:
            bad.append("reshape pass fired on a healthy cluster: "
                       f"{resh.get('no_outage_reshapes')} reshapes")
        if resh.get("no_outage_identical") is not True:
            bad.append("idle reshape pass CHANGED placements")
        if (resh.get("evictions_per_pod_hour", 1e9)
                > resh.get("budget_per_pod_hour", 0.0)):
            bad.append("disruption "
                       f"{resh.get('evictions_per_pod_hour')} over "
                       f"budget {resh.get('budget_per_pod_hour')}")
        if not small and resh.get("recovered_frac", 0.0) <= 0.5:
            bad.append("recovered "
                       f"{resh.get('recovered_frac')} <= 0.5 of "
                       "oracle bandwidth gain")
        if bad:
            print("WARNING: reshape bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)
    if name == "scenario":
        detail = res.metrics.get("detail", {})
        # Structural bars hold at every shape: gang atomicity, a
        # shape-clean scorecard, no silent queue drops, no double
        # binds.  The >=1M streamed-pods floor is a full-shape
        # property — smoke runs stream a few hundred.
        bad = []
        if detail.get("half_moved_gangs", 1) != 0:
            bad.append("half_moved_gangs="
                       f"{detail.get('half_moved_gangs')}")
        if detail.get("scorecard_problems", ["missing"]):
            bad.append("scorecard shape problems: "
                       f"{detail.get('scorecard_problems')}")
        if detail.get("queue_dropped", 1) != 0:
            bad.append(f"queue_dropped={detail.get('queue_dropped')}"
                       " — pods silently vanished from the informer "
                       "queue")
        if detail.get("pods_double_bound", 1) != 0:
            bad.append("pods_double_bound="
                       f"{detail.get('pods_double_bound')}")
        integ = (detail.get("scorecard", {}).get("repair_events", {})
                 .get("integrity", {}))
        if integ.get("unrepaired", 0) != 0:
            bad.append(f"integrity.unrepaired={integ.get('unrepaired')}"
                       " — a state fault survived the r10 auditor")
        if not small and detail.get("pods_streamed", 0) < 1_000_000:
            bad.append(f"streamed {detail.get('pods_streamed')} "
                       "< 1M pods at the full shape")
        if bad:
            print("WARNING: scenario bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)
    if name == "fleet":
        flt = res.metrics.get("detail", {}).get("fleet", {})
        # Isolation is structural and holds at every shape.  The
        # consolidation speedup and the transfer win are full-shape
        # properties (smoke shapes under-train the policy and let
        # snapshot-rebuild spikes dominate tiny drains).
        bad = []
        if flt.get("isolation_bit_identical") is not True:
            bad.append("a tenant's placements DIVERGED from solo "
                       "serving")
        if not small and not flt.get("speedup_over_4x"):
            bad.append(f"consolidation speedup {flt.get('speedup')} "
                       "< 4x the single-tenant rate")
        if not small and not flt.get("transfer", {}).get(
                "warm_lt_cold"):
            bad.append("warm-started tenant did not promote with "
                       "strictly fewer examples than cold "
                       f"(warm={flt.get('transfer', {}).get('examples_to_promotion_warm')}, "
                       f"cold={flt.get('transfer', {}).get('examples_to_promotion_cold')})")
        if bad:
            print("WARNING: fleet bars unmet: " + "; ".join(bad),
                  file=sys.stderr)
            sys.exit(1)


def main() -> None:
    if "--chaos" in sys.argv[1:]:
        _run_chaos_bench()
        return
    argv = sys.argv[1:]
    if "--suite" in argv:
        idx = argv.index("--suite")
        if idx + 1 >= len(argv):
            print("ERROR: --suite needs a config name", file=sys.stderr)
            sys.exit(2)
        _run_suite_bench(argv[idx + 1])
        return
    if "--trace-out" in argv:
        # Flight-recorder trace artifact leg: the density run dumps
        # its recorder (Chrome trace-event JSON, Perfetto-loadable,
        # lint with tools/trace_check.py) to this path.  Stored in the
        # env so comparison-mode child legs inherit it.
        idx = argv.index("--trace-out")
        if idx + 1 >= len(argv):
            print("ERROR: --trace-out needs a path", file=sys.stderr)
            sys.exit(2)
        os.environ["BENCH_TRACE_OUT"] = argv[idx + 1]
    tpu_ok = True
    force_cpu = os.environ.get("BENCH_FORCE_CPU", "") == "1"
    if "BENCH_CHILD" not in os.environ and not force_cpu:
        # Signal the round-long watcher (tools/tpu_watch.py) to yield
        # the single-owner chip to this run.  Forced-CPU runs never
        # need the chip, so they must not stall the watcher.
        _mark_driver_active()
        import atexit

        atexit.register(_clear_driver_intent)
    if force_cpu:
        # Set for backend-subprocesses of a CPU-fallback parent: the
        # axon sitecustomize overrides JAX_PLATFORMS, so without this
        # the child would hang on the same wedged-tunnel init the
        # parent already dodged.
        tpu_ok = False
        import jax

        jax.config.update("jax_platforms", "cpu")
        ndev = os.environ.get("BENCH_CPU_DEVICES", "")
        if ndev:
            # Virtual multi-device CPU: exercises the BENCH_MESH path
            # without hardware (mirrors tests/conftest.py — including
            # the fallback for jax versions without the config option,
            # which works because the backend is not initialized yet).
            try:
                jax.config.update("jax_num_cpu_devices", int(ndev))
            except AttributeError:
                flags = os.environ.get("XLA_FLAGS", "")
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{int(ndev)}").strip()
    elif os.environ.get("BENCH_SKIP_TPU_PROBE", "") != "1" \
            and not _tpu_reachable_with_retries():
        persisted = _persisted_tpu_density()
        if persisted is not None:
            # The tunnel is wedged NOW, but the round-long watcher
            # caught a recovery window and ran the full bench on
            # hardware; replay that artifact rather than measure a
            # CPU stand-in.
            print("WARNING: TPU unreachable now; replaying the "
                  "persisted mid-round TPU measurement "
                  f"({persisted['detail'].get('measured_at', '?')})",
                  file=sys.stderr)
            persisted["detail"].update(_probe_log_stats())
            if "BENCH_CHILD" not in os.environ:
                # Unreachable for children today (they always carry
                # BENCH_SKIP_TPU_PROBE=1), but the certify/augment-
                # once invariant should hold locally, not by distant
                # env plumbing.
                _attach_north_star(persisted)
                _attach_cpu_density(persisted)
            _attach_bench_env(persisted)
            print(json.dumps(persisted))
            return
        # Degrade to CPU instead of hanging the driver: the JSON line
        # still appears, flagged via detail.backend (reported from
        # jax.default_backend() after the run, so it is always the
        # backend that actually executed).
        tpu_ok = False
        force_cpu = True
        import jax

        jax.config.update("jax_platforms", "cpu")
        print("WARNING: TPU unreachable (tunnel wedged?); benching on "
              "CPU", file=sys.stderr)
    # Defaults are the BASELINE.json north-star config: 5k nodes
    # (padded to a 128 multiple), p99 Score() < 5 ms, >=10k pods/sec.
    num_nodes = int(os.environ.get("BENCH_NODES", "5120"))
    num_pods = int(os.environ.get("BENCH_PODS", "65536"))
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    method = os.environ.get("BENCH_METHOD", "parallel")
    # pipeline: chunked device replay with an async bind worker AND
    # true per-chunk score-latency percentiles (device mode's single
    # dispatch can only report an amortized mean).  512 batches at
    # chunk_batches=16 give 32 independent per-chunk latency samples
    # while amortizing the tunneled chip's ~65 ms per-fetch transport
    # cost to ~4 ms/batch (device-side compute is ~1 ms/batch; a
    # non-tunneled deployment would see that directly).
    mode = os.environ.get("BENCH_MODE", "pipeline")
    chunk_batches = int(os.environ.get("BENCH_CHUNK_BATCHES", "16"))
    # Seeded link-probe/metrics churn per serving cycle (r7): keeps
    # static_version moving through the measured window so the
    # artifact reports the incremental-refresh machinery under load
    # (static_refresh block in detail).  BENCH_CHURN_LINKS=0 reverts
    # to the churn-free drain.  Read from env by comparison-mode child
    # legs too (env propagates through _run_backend_subprocess).
    churn_links = int(os.environ.get("BENCH_CHURN_LINKS", "4"))

    # Score-kernel backend comparison (dense XLA vs tiled Pallas):
    # "both" runs the full workload under each and headlines the
    # winner — the measured basis for deploy configs' score_backend.
    # Pallas off-TPU only has the interpreter (orders of magnitude
    # slow at N=5120), so the CPU fallback pins to xla.  tpu_ok comes
    # from the subprocess PROBE, not jax.default_backend(): in
    # comparison mode the parent must never initialize a backend — the
    # TPU is single-owner, and a parent holding it would wedge every
    # child leg's PJRT init.
    backend_env = os.environ.get("BENCH_SCORE_BACKEND",
                                 "both" if tpu_ok else "xla")
    backends = (["xla", "pallas"] if backend_env == "both"
                else [backend_env])

    results = {}
    errors = {}
    mesh_desc = ""
    mesh_error = ""
    if len(backends) > 1:
        # Comparison mode: EVERY leg in its own killable subprocess
        # (sequential, so each owns the chip in turn); a hung compile
        # (e.g. first-ever Mosaic lowering on new hardware) costs one
        # timeout, not the other leg's measurement.
        for backend in backends:
            # Per-LEG probe (VERDICT r3 #1a): the tunnel can wedge
            # between legs; a cheap re-probe converts that into a
            # recorded per-leg error instead of a 900 s hang.  Two
            # attempts with a 30 s backoff: the chip takes a few
            # seconds to release after the previous leg's process
            # exits, and that transient cost round 4's first xla
            # comparison leg.
            reachable = force_cpu
            for attempt in range(2):
                if reachable or _tpu_reachable(timeout_s=60):
                    reachable = True
                    break
                if attempt == 0:
                    time.sleep(30)
            if not reachable:
                errors[backend] = "per-leg TPU probe failed"
                print(f"WARNING: skipping {backend} leg: tunnel "
                      "unreachable at leg start", file=sys.stderr)
                continue
            try:
                results[backend] = _run_backend_subprocess(
                    backend, force_cpu=force_cpu)
            except Exception as exc:  # noqa: BLE001 — a failing
                # backend must not discard the other's measurement:
                # the headline line is the driver's only artifact.
                errors[backend] = f"{type(exc).__name__}: {exc}"
                print(f"WARNING: {backend} backend bench failed: "
                      f"{errors[backend]}", file=sys.stderr)
    else:
        from kubernetesnetawarescheduler_tpu.bench.density import (
            run_density,
        )

        import contextlib

        import jax

        # Multi-chip: shard the replay's node axis over every visible
        # device (a real v5e-4 exposes 4; the tunneled dev chip 1, so
        # "auto" is a no-op there).  BENCH_MESH=off disables;
        # BENCH_MESH=dp,tp picks an explicit shape.
        mesh = None
        mesh_error = ""
        mesh_env = os.environ.get("BENCH_MESH", "auto")
        if mesh_env != "off" and mode != "host":
            # Soft-fail parsing/construction: a bad BENCH_MESH value
            # must not cost the driver its only artifact (the JSON
            # line) — run unmeshed and say so in the detail.
            try:
                from kubernetesnetawarescheduler_tpu.parallel.sharding \
                    import make_mesh

                if mesh_env == "auto":
                    if jax.device_count() > 1:
                        mesh = make_mesh(1, jax.device_count())
                else:
                    dp, tp = (int(x) for x in mesh_env.split(","))
                    mesh = make_mesh(dp, tp)
            except Exception as exc:  # noqa: BLE001
                mesh_error = f"{type(exc).__name__}: {exc}"
                print(f"WARNING: BENCH_MESH={mesh_env!r} rejected "
                      f"({mesh_error}); running unmeshed",
                      file=sys.stderr)
        if mesh is not None and mode == "pipeline":
            # The pipelined drain has no mesh variant; the sharded
            # monolithic replay is the multi-chip throughput path
            # (run_density raises on pipeline+mesh — the demotion is
            # decided HERE, where the reported mode label lives).
            mode = "device"
        mesh_desc = ("x".join(str(mesh.shape[a]) for a in ("dp", "tp"))
                     if mesh is not None else "")

        profile_dir = os.environ.get("BENCH_PROFILE", "")
        if profile_dir:
            # JAX profiler trace of the measured window (SURVEY.md §5
            # tracing row): view with tensorboard or xprof.
            trace_cm = jax.profiler.trace(profile_dir)
        else:
            trace_cm = contextlib.nullcontext()
        backend = backends[0]
        trace_out = os.environ.get("BENCH_TRACE_OUT", "")
        if trace_out and "BENCH_CHILD" in os.environ:
            # Comparison-mode legs share the parent env: suffix per
            # backend so the two children don't clobber one dump.
            trace_out = f"{trace_out}.{backend}"
        try:
            with trace_cm:
                res = run_density(
                    num_nodes=num_nodes, num_pods=num_pods,
                    batch_size=batch, method=method, mode=mode,
                    chunk_batches=chunk_batches, score_backend=backend,
                    mesh=mesh, churn_links=churn_links,
                    trace_out=trace_out or None,
                    # r16: the host-mode drain serves through the
                    # persistent K-cycle window with coalesced async
                    # binds (pipeline/device modes ignore these — the
                    # monolithic replay is already one dispatch).
                    multicycle=int(os.environ.get(
                        "BENCH_MULTICYCLE", "8")) if mode == "host"
                    else 1,
                    bind_coalesce_window=int(os.environ.get(
                        "BENCH_BIND_COALESCE", "4")),
                    bind_max_inflight=int(os.environ.get(
                        "BENCH_BIND_INFLIGHT", "2")),
                    # Host mode defaults to the three-stage pipelined
                    # datapath (encode-ahead ∥ device step ∥ async
                    # bind); BENCH_HOST_PIPELINED=0 reverts to the
                    # serial loop for A/B comparison.
                    pipelined=(mode == "host" and os.environ.get(
                        "BENCH_HOST_PIPELINED", "1") == "1"))
        except Exception as exc:  # noqa: BLE001
            errors[backend] = f"{type(exc).__name__}: {exc}"
            res = None
        executed_backend = jax.default_backend()
        if res is not None:
            # The device-boundary microbench shares this process (and
            # so the single-owner chip) with the drain above.
            device_lat = _measure_device_leg(num_nodes, batch, backend)
            # r16 legs: the K-window boundary microbench and the
            # K=1-vs-K placement-identity A/B (both opt out via
            # BENCH_MULTICYCLE<=1).
            multicycle_lat = _measure_multicycle_leg(num_nodes, batch,
                                                     backend)
            multicycle_ab = _multicycle_identity_leg()
            results[backend] = _assemble_doc(
                res, num_nodes=num_nodes, batch=batch, method=method,
                mode=mode, executed_backend=executed_backend,
                score_backend=backend, mesh_desc=mesh_desc,
                device_lat=device_lat, multicycle_lat=multicycle_lat,
                multicycle_ab=multicycle_ab)
    if (not results and not force_cpu
            and "BENCH_CHILD" not in os.environ):
        # Top-level invocations only: a comparison-mode CHILD leg
        # (marked via BENCH_CHILD) must fail loudly instead — a
        # silent CPU stand-in would corrupt the TPU backend
        # comparison the parent is assembling.
        # The probe can succeed and the tunnel still wedge mid-leg
        # (observed: jax.devices() ok at T+0, full run hung at T+20min).
        # The driver's only artifact is this script's stdout — a CPU
        # fallback line with the TPU errors attached beats a nonzero
        # exit with nothing.
        persisted = _persisted_tpu_density()
        if persisted is not None:
            # Same preference as the startup-probe fallback: a real
            # persisted hardware measurement beats a CPU stand-in.
            print(f"WARNING: all TPU legs failed ({errors}); replaying "
                  "the persisted mid-round TPU measurement",
                  file=sys.stderr)
            persisted["detail"].update(_probe_log_stats())
            for backend, err in errors.items():
                persisted["detail"][f"{backend}_error"] = err
            _attach_north_star(persisted)
            _attach_cpu_density(persisted)
            _attach_bench_env(persisted)
            print(json.dumps(persisted))
            return
        print(f"WARNING: all TPU legs failed ({errors}); falling back "
              "to CPU", file=sys.stderr)
        try:
            # Generous explicit timeout: the 900s default is sized for
            # TPU legs; the CPU density run at full scale can exceed it
            # and this leg is the last line of defense for the JSON.
            # (_measure_device_leg self-trims its reps on CPU.)
            results["xla"] = _run_backend_subprocess(
                "xla", force_cpu=True, timeout_s=7200)
        except Exception as exc:  # noqa: BLE001
            errors["cpu-fallback"] = f"{type(exc).__name__}: {exc}"
    if not results:
        raise SystemExit(f"all score backends failed: {errors}")
    best = max(results, key=lambda b: float(results[b]["value"]))
    doc = results[best]
    detail = doc["detail"]
    for backend, r in results.items():
        if backend != best:
            detail[f"{backend}_pods_per_sec"] = r["value"]
            detail[f"{backend}_score_p50_ms"] = \
                r["detail"].get("score_p50_ms")
    for backend, err in errors.items():
        detail[f"{backend}_error"] = err
    if mesh_error:
        detail["mesh_error"] = mesh_error
    if "BENCH_CHILD" not in os.environ:
        # Top-level assembly only: children emit their leg's doc
        # verbatim and the parent certifies/augments once.
        _attach_north_star(doc)
        if detail.get("backend") == "tpu":
            _attach_cpu_density(doc)
        if detail.get("backend") != "tpu":
            # CPU fallback: attach the watcher's round-long probe
            # record as proof the tunnel was tried continuously, not
            # just at startup.
            detail.update(_probe_log_stats())
    _attach_bench_env(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
